"""Device-native splitting (reference ``Splitting_Emitter_GPU`` /
``split_gpu``, ``splitting_emitter_gpu.hpp:53``): a JAX-traceable split
function compiles to one masked-compaction program per branch, so device
batches are split without a host round-trip; Python/multicast split
functions fall back to the host path."""

import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.parallel.emitters import SplittingEmitter


N = 512


def _graph(split_fn):
    evens, odds = [], []
    g = wf.PipeGraph("dev_split")
    src = (wf.Source_Builder(lambda: iter({"v": i} for i in range(N)))
           .withOutputBatchSize(64).build())
    mp = g.add_source(src).add(
        wf.MapTPU_Builder(lambda t: {"v": t["v"] * 2}).build())
    mp.split(split_fn, 2)
    mp.select(0).add_sink(wf.Sink_Builder(
        lambda t: evens.append(t["v"]) if t is not None else None).build())
    mp.select(1).add_sink(wf.Sink_Builder(
        lambda t: odds.append(t["v"]) if t is not None else None).build())
    g.run()
    src_rep = src.replicas[0]
    # the splitting emitter sits on the TPU map's replicas
    split_em = None
    for op in g._operators:
        for rep in op.replicas:
            if isinstance(rep.emitter, SplittingEmitter):
                split_em = rep.emitter
    return evens, odds, split_em


def test_device_native_split():
    # traceable single-destination split: (v/2) % 2 routes by parity
    evens, odds, em = _graph(lambda t: (t["v"] // 2) % 2)
    assert sorted(evens) == [2 * i for i in range(N) if i % 2 == 0]
    assert sorted(odds) == [2 * i for i in range(N) if i % 2 == 1]
    # the compiled device split (not the host fallback) actually ran
    assert em is not None and any(v is not None
                                  for v in em._device_splits.values())


def test_python_split_falls_back_to_host():
    def split(t):  # data-dependent Python control flow: not traceable
        if t["v"] % 4 == 0:
            return 0
        return 1

    evens, odds, em = _graph(split)
    assert sorted(evens) == [2 * i for i in range(N) if (2 * i) % 4 == 0]
    assert sorted(odds) == [2 * i for i in range(N) if (2 * i) % 4 != 0]
    assert em is not None and all(v is None
                                  for v in em._device_splits.values())


def test_multicast_split_falls_back_and_isolates():
    # iterable-returning split fn: both branches get every tuple; in-place
    # mutation on one branch must not leak (COW through the fallback path)
    seen0, seen1 = [], []
    g = wf.PipeGraph("dev_split_multi")
    src = (wf.Source_Builder(lambda: iter({"v": i} for i in range(128)))
           .withOutputBatchSize(32).build())
    mp = g.add_source(src).add(
        wf.MapTPU_Builder(lambda t: {"v": t["v"]}).build())
    mp.split(lambda t: (0, 1), 2)

    def bump(t):
        t["v"] += 1000
        return None

    mp.select(0).add(wf.Map(bump)).add_sink(wf.Sink_Builder(
        lambda t: seen0.append(t["v"]) if t is not None else None).build())
    mp.select(1).add_sink(wf.Sink_Builder(
        lambda t: seen1.append(t["v"]) if t is not None else None).build())
    g.run()
    assert sorted(seen0) == [i + 1000 for i in range(128)]
    assert sorted(seen1) == list(range(128))


def test_python_split_to_host_branches_ok_with_tpu_branch_elsewhere():
    """A non-traceable split whose tuples only ever route to HOST branches
    keeps working even when another branch is device-only — the host
    fallback raises lazily, per routed tuple, not eagerly at the first
    device batch."""
    host_seen = []
    g = wf.PipeGraph("lazy_split_guard")
    src = (wf.Source_Builder(lambda: iter({"v": i} for i in range(128)))
           .withOutputBatchSize(32).build())
    mp = g.add_source(src).add(
        wf.MapTPU_Builder(lambda t: {"v": t["v"]}).build())

    def split(t):  # Python control flow (not traceable); always branch 0
        if t["v"] >= 0:
            return 0
        return 1

    mp.split(split, 2)
    mp.select(0).add_sink(wf.Sink_Builder(
        lambda t: host_seen.append(t["v"]) if t is not None else None)
        .build())
    # branch 1 is a device-only continuation that never receives tuples
    mp.select(1).add(
        wf.MapTPU_Builder(lambda t: {"v": t["v"] * 2}).build()) \
      .add_sink(wf.Sink_Builder(lambda t: None).build())
    g.run()
    assert sorted(host_seen) == list(range(128))


def test_python_split_routing_to_tpu_branch_raises():
    """The lazy guard still fires with the clear message when a tuple IS
    routed to the device-only branch through the host fallback."""
    import pytest
    g = wf.PipeGraph("lazy_split_guard_bad")
    src = (wf.Source_Builder(lambda: iter({"v": i} for i in range(128)))
           .withOutputBatchSize(32).build())
    mp = g.add_source(src).add(
        wf.MapTPU_Builder(lambda t: {"v": t["v"]}).build())

    def split(t):
        if t["v"] % 2 == 0:
            return 0
        return 1

    mp.split(split, 2)
    mp.select(0).add_sink(wf.Sink_Builder(lambda t: None).build())
    mp.select(1).add(
        wf.MapTPU_Builder(lambda t: {"v": t["v"] * 2}).build()) \
      .add_sink(wf.Sink_Builder(lambda t: None).build())
    with pytest.raises(wf.WindFlowError, match="JAX-traceable"):
        g.run()
