"""Pallas TPU kernels for the FFAT hot loop (windflow_tpu/kernels,
docs/PERF.md round 14): record-for-record A/B of the kernel-backed
programs against the ``WF_TPU_PALLAS=0`` lax path across the
window_cb / window_tb / dense-reduce / compacted families (including
TB ring regrow and CB EOS-flush edges), kernel-level bit-equality
against the lax compositions they replace, the zero-dispatch-delta pin
through the jit registry, chaos kill→restore→diff with the kernels on,
the WF607 forced-downgrade warnings, the off-path budget (the kill
switch builds NO kernels), and the key-aligned mesh ingest extension
to the sharded dense reduce / stateful paths (this PR's ROADMAP
item-4 satellite).

Tier-1 runs the kernels under the Pallas interpreter
(``interpret=True`` — the real kernel bodies, emulated on CPU);
Mosaic-compiled behavior is the same trace on a TPU backend."""

import dataclasses
import warnings
from collections import defaultdict

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu import kernels as pk
from windflow_tpu.basic import Config, default_config
from windflow_tpu.monitoring.jit_registry import default_registry
from windflow_tpu.windows import ffat_kernels as fk
from windflow_tpu.windows.grouping import dense_rank, invert_perm, \
    order_and_hist


def _cfg(pallas, **kw):
    return dataclasses.replace(default_config, pallas_kernels=pallas,
                               **kw)


# ---------------------------------------------------------------------------
# gate resolution
# ---------------------------------------------------------------------------

def test_resolution_modes():
    """auto on the CPU backend selects the kernels under the
    interpreter (tier-1 executes the real bodies); "0" is the kill
    switch; forcing on CPU also interprets."""
    assert jax.default_backend() == "cpu"
    auto = pk.resolve_pallas(Config(pallas_kernels="auto"))
    assert auto is not None and auto.interpret
    assert pk.resolve_pallas(Config(pallas_kernels="0")) is None
    assert pk.resolve_pallas(Config(pallas_kernels=False)) is None
    forced = pk.resolve_pallas(Config(pallas_kernels="1"))
    assert forced is not None and forced.interpret
    assert pk.pallas_forced(Config(pallas_kernels="1"))
    assert not pk.pallas_forced(Config(pallas_kernels="auto"))


def test_kill_switch_builds_no_kernels():
    """Off-path budget: under WF_TPU_PALLAS=0 the step builders resolve
    once and build ZERO pallas_calls — the lax path verbatim."""
    before = pk.pallas_build_count()
    step = fk.make_ffat_step(64, 4, 4, 4, 1, lambda t: t["v"],
                             lambda a, b: a + b, lambda t: t["k"],
                             monoid="sum", pallas=None)
    state = fk.make_ffat_state(jnp.zeros((), jnp.int64), 4, 4)
    payload = {"k": jnp.arange(64, dtype=jnp.int32) % 4,
               "v": jnp.arange(64, dtype=jnp.int64)}
    jax.jit(step)(state, payload, jnp.arange(64, dtype=jnp.int64),
                  jnp.ones(64, bool))
    assert pk.pallas_build_count() == before
    # and the active path builds at least one per region
    step_p = fk.make_ffat_step(64, 4, 4, 4, 1, lambda t: t["v"],
                               lambda a, b: a + b, lambda t: t["k"],
                               monoid="sum",
                               pallas=pk.PallasMode(interpret=True))
    jax.jit(step_p)(state, payload, jnp.arange(64, dtype=jnp.int64),
                    jnp.ones(64, bool))
    assert pk.pallas_build_count() > before


# ---------------------------------------------------------------------------
# kernel-level bit-equality against the lax compositions
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("B,NB", [(8, 5), (256, 5), (257, 1025),
                                  (1000, 2), (3, 3), (512, 257),
                                  (4096, 4096)])
def test_grouping_kernel_matches_lax(B, NB):
    """order/rank/hist from the one-pass kernel == the counting-sort
    trio (order_and_hist / dense_rank) bit for bit, across tile edges
    (B % 256), bucket-pad edges (NB % 128), and the gate ceiling."""
    rng = np.random.default_rng(B * 31 + NB)
    ids = jnp.asarray(rng.integers(0, NB, B), jnp.int32)
    dest, rank, hist = jax.jit(
        lambda i: pk.grouping_rank_hist(i, NB, True))(ids)
    order_ref, hist_ref = order_and_hist(ids, NB)
    rank_ref, counts_ref, _, _ = dense_rank(ids, NB)
    assert np.array_equal(np.asarray(hist), np.asarray(hist_ref))
    assert np.array_equal(np.asarray(invert_perm(dest)),
                          np.asarray(order_ref))
    assert np.array_equal(np.asarray(rank), np.asarray(rank_ref)[:B])
    assert np.array_equal(np.asarray(hist)[:NB],
                          np.asarray(counts_ref))


def test_grouping_gate_bounds():
    from windflow_tpu.kernels.pallas_ffat import MAX_BUCKETS, MAX_LANES
    assert not pk.grouping_supported(64, MAX_BUCKETS + 1)
    assert not pk.grouping_supported(MAX_LANES + 1, 16)
    assert pk.grouping_supported(64, 16)


@pytest.mark.parametrize("monoid", ["sum", "max", "min"])
@pytest.mark.parametrize("dt", [jnp.int32, jnp.int64, jnp.float32,
                                jnp.float64])
def test_sliding_fold_matches_lax(monoid, dt):
    """The pane-combine kernel against _monoid_fill +
    _sliding_reduce_plain: bit-identical for max/min/int-sum by
    identical combine schedule; f32 sums ride the MXU banded matmul —
    exact on integer-valued data (this test), psum-grade otherwise."""
    rng = np.random.default_rng(7)
    for K, NPP, R in [(4, 10, 3), (7, 33, 8), (128, 300, 1),
                      (3, 9, 9), (16, 130, 7), (1, 5, 5)]:
        vals = {"a": jnp.asarray(rng.integers(-50, 50, (K, NPP)), dt),
                "b": jnp.asarray(rng.integers(0, 9, (K, NPP)), dt)}
        valid = jnp.asarray(rng.random((K, NPP)) < 0.7)
        op = {"sum": jnp.add, "max": jnp.maximum,
              "min": jnp.minimum}[monoid]
        comb = lambda x, y: jax.tree.map(op, x, y)
        ref = jax.jit(lambda v, va: fk._sliding_reduce_plain(
            comb, va, v, R, 1, monoid))(vals, valid)
        got = jax.jit(lambda v, va: pk.sliding_fold(
            v, va, R, monoid, True))(vals, valid)
        for k in vals:
            assert np.array_equal(np.asarray(got[k]),
                                  np.asarray(ref[k])), (K, NPP, R, k)


def test_fold_gate_bounds():
    """fold_supported mirrors table_leaf_ok's backend stance: compiled
    Mosaic keeps to f32/i32 (int64 pane aggregates fall back to lax on
    a real TPU — CPU tier-1 cannot observe a Mosaic lowering failure,
    so the gate must), bool is excluded everywhere, and the pane axis
    is bounded by the VMEM block (MAX_FOLD_PANES)."""
    from windflow_tpu.kernels.pallas_ffat import MAX_FOLD_PANES
    v32 = {"a": jnp.zeros((4, 16), jnp.float32)}
    v64 = {"a": jnp.zeros((4, 16), jnp.int64)}
    vb = {"a": jnp.zeros((4, 16), jnp.bool_)}
    assert pk.fold_supported(v32, 4, "sum", True)
    assert pk.fold_supported(v32, 4, "sum", False)
    assert pk.fold_supported(v64, 4, "max", True)
    assert not pk.fold_supported(v64, 4, "max", False)
    assert not pk.fold_supported(vb, 4, "max", True)
    assert not pk.fold_supported(v32, 4, None, True)
    wide = {"a": jnp.zeros((4, MAX_FOLD_PANES + 1), jnp.float32)}
    assert not pk.fold_supported(wide, 4, "sum", True)


def test_sliding_fold_float_sum_tolerance():
    """Non-integer f32 sums: the banded matmul reassociates (the psum
    tolerance the declared-"sum" contract already implies) — close, not
    necessarily bitwise."""
    rng = np.random.default_rng(11)
    vals = jnp.asarray(rng.random((8, 64), np.float32))
    valid = jnp.ones((8, 64), bool)
    comb = lambda a, b: a + b
    ref = fk._sliding_reduce_plain(comb, valid, vals, 5, 1, "sum")
    got = pk.sliding_fold(vals, valid, 5, "sum", True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5)


@pytest.mark.parametrize("monoid", ["sum", "max", "min"])
def test_dense_table_matches_scatter(monoid):
    """The segmented-reduce kernel against the one-scatter combine:
    slot tables, packed [B, W] carrier columns, the ts max column, and
    the liveness count, across slot-space edges."""
    rng = np.random.default_rng(5)
    for B, S in [(64, 8), (300, 17), (100, 4096), (5, 1)]:
        row = jnp.asarray(rng.integers(0, S + 1, B), jnp.int32)
        v1 = jnp.asarray(rng.integers(-100, 100, B), jnp.int64)
        v2 = jnp.asarray(rng.integers(0, 50, (B, 3)), jnp.float32)
        ts = jnp.asarray(rng.integers(0, 10 ** 9, B), jnp.int64)
        i1 = pk.monoid_identity_py(monoid, v1.dtype)
        i2 = pk.monoid_identity_py(monoid, v2.dtype)

        def lax_ref(row, v1, v2, ts):
            b1 = jnp.full((S + 1,), i1, v1.dtype)
            t1 = fk._monoid_scatter(b1.at[row], monoid)(v1)[:S]
            b2 = jnp.full((S + 1, 3), i2, v2.dtype)
            t2 = fk._monoid_scatter(b2.at[row], monoid)(v2)[:S]
            t3 = jnp.full(S + 1, -1, jnp.int64).at[row].max(ts)[:S]
            return t1, t2, t3

        r1, r2, r3 = jax.jit(lax_ref)(row, v1, v2, ts)
        g1, g2, g3 = jax.jit(lambda r, a, b, t: pk.dense_monoid_table(
            r, [a, b, t], [monoid, monoid, "max"], [i1, i2, -1], S,
            True))(row, v1, v2, ts)
        for g, r_ in [(g1, r1), (g2, r2), (g3, r3)]:
            assert np.array_equal(np.asarray(g), np.asarray(r_)), \
                (B, S, monoid)


# ---------------------------------------------------------------------------
# graph-level record-for-record A/B (pallas vs kill switch)
# ---------------------------------------------------------------------------

def _run_cb(pallas, monoid, n=500, batch=64):
    out = []
    op = (lambda a, b: a + b) if monoid in (None, "sum") \
        else (lambda a, b: jnp.maximum(a, b))
    src = (wf.Source_Builder(lambda: iter(
        [{"key": i % 5, "v": float(i % 97)} for i in range(n)]))
        .withOutputBatchSize(batch).build())
    wb = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"], op)
          .withCBWindows(16, 4).withKeyBy(lambda t: t["key"])
          .withMaxKeys(5))
    if monoid:
        wb = wb.withMonoidCombiner(monoid)
    g = wf.PipeGraph(f"pcb_{pallas}_{monoid}", config=_cfg(pallas))
    g.add_source(src).add(wb.build()).add_sink(
        wf.Sink_Builder(lambda r: out.append(
            (int(r["key"]), int(r["wid"]), float(r["value"])))
            if r is not None else None).build())
    g.run()
    return out


@pytest.mark.parametrize("monoid", ["sum", "max", None])
def test_window_cb_record_identical(monoid):
    """CB windows (grouping + pane-combine kernels on the monoid path,
    grouping alone on the generic path), incl. the partial-window EOS
    flush riding the same restored state: pallas on == kill switch,
    record for record."""
    a = _run_cb("auto", monoid)
    b = _run_cb("0", monoid)
    assert a and a == b


def _run_tb(pallas, jump=False):
    out = []
    n = 400

    def ts_of(i):
        # a mid-stream time jump widens the pane span past the
        # first-batch estimate, forcing the auto-sized ring to REGROW —
        # the rebuilt step must keep its pallas selection
        return i * 1000 + (300_000 if jump and i >= n // 2 else 0)

    src = (wf.Source_Builder(lambda: iter(
        [{"key": i % 4, "v": i, "ts": ts_of(i)} for i in range(n)]))
        .withTimestampExtractor(lambda t: t["ts"])
        .withOutputBatchSize(48).build())
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                    lambda a, b: a + b)
         .withTBWindows(16000, 4000).withKeyBy(lambda t: t["key"])
         .withMaxKeys(4).build())
    g = wf.PipeGraph(f"ptb_{pallas}_{jump}", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT, config=_cfg(pallas))
    g.add_source(src).add(w).add_sink(
        wf.Sink_Builder(lambda r: out.append(
            (int(r["key"]), int(r["wid"]), int(r["value"])))
            if r is not None else None).build())
    g.run()
    return out, w


@pytest.mark.parametrize("jump", [False, True])
def test_window_tb_record_identical(jump):
    """TB windows (the (key, pane) grouping kernel) incl. the
    EOS-flush loop; jump=True drives a mid-stream ring REGROW, whose
    step rebuild must keep the kernels (and stay record-identical)."""
    a, wa = _run_tb("auto", jump)
    b, wb = _run_tb("0", jump)
    assert a and sorted(a) == sorted(b)
    if jump:
        assert wa.NP > 2 * wa.R     # the regrow actually happened
        assert wa._tb_counter("n_evicted") == 0


def _run_dense_reduce(pallas, n=600):
    out = []
    src = (wf.Source_Builder(lambda: iter(
        [{"key": i % 23, "v": i * 3} for i in range(n)]))
        .withOutputBatchSize(128).build())
    r = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": a["key"], "v": a["v"] + b["v"]})
         .withKeyBy(lambda t: t["key"]).withMaxKeys(23)
         .withMonoidCombiner("sum").build())
    g = wf.PipeGraph(f"pdr_{pallas}",
                     config=_cfg(pallas, key_compaction=False))
    g.add_source(src).add(r).add_sink(
        wf.Sink_Builder(lambda t: out.append((int(t["key"]),
                                              int(t["v"])))
                        if t is not None else None).build())
    g.run()
    return out


def test_dense_reduce_record_identical():
    a = _run_dense_reduce("auto")
    b = _run_dense_reduce("0")
    assert a and a == b


def _run_compacted(pallas, monoid, n=800):
    out = []
    comb = (lambda a, b: {"key": a["key"], "v": a["v"] + b["v"]}) \
        if monoid == "sum" else \
        (lambda a, b: {"key": a["key"],
                       "v": jnp.maximum(a["v"], b["v"])})
    src = (wf.Source_Builder(lambda: iter(
        [{"key": (i * 2654435761) % 10007, "v": i % 1000}
         for i in range(n)]))
        .withOutputBatchSize(256).build())
    r = (wf.ReduceTPU_Builder(comb)
         .withKeyBy(lambda t: t["key"]).withMonoidCombiner(monoid)
         .build())
    g = wf.PipeGraph(f"pcr_{pallas}_{monoid}", config=_cfg(pallas))
    g.add_source(src).add(r).add_sink(
        wf.Sink_Builder(lambda t: out.append((int(t["key"]),
                                              int(t["v"])))
                        if t is not None else None).build())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        g.run()
    return out


@pytest.mark.parametrize("monoid", ["sum", "max"])
def test_compacted_reduce_record_identical(monoid):
    """The compacted arbitrary-key path: the dense half's one-scatter
    combine (packed int64 carrier under max, per-leaf under sum) rides
    the segmented-reduce kernel; the overflow/sorted lane and the rank
    merge are unchanged — output record-identical to the kill switch."""
    a = _run_compacted("auto", monoid)
    b = _run_compacted("0", monoid)
    assert a and a == b


# ---------------------------------------------------------------------------
# zero dispatch delta + chaos restore
# ---------------------------------------------------------------------------

def test_zero_dispatch_delta():
    """The kernels trace INTO the existing wf_jit programs: the jit
    registry's per-program dispatch counts are identical between pallas
    on and the kill switch — zero extra programs, zero extra
    dispatches per batch."""
    snaps = {}
    for pallas in ("auto", "0"):
        default_registry().reset()
        _run_cb(pallas, "sum", n=512, batch=64)
        snaps[pallas] = {k: v["dispatches"]
                        for k, v in default_registry().snapshot().items()}
    assert snaps["auto"] == snaps["0"]


def test_chaos_kill_restore_diff_with_pallas(tmp_path):
    """Durability chaos with the kernels ON: kill mid-epoch on the
    fused map→CB-window chain, restore, diff record-for-record — the
    restored graph rebuilds its step programs with the same pallas
    selection (snapshot/restore carries no kernel state; programs are
    rebuilt through _build_step)."""
    from windflow_tpu.durability import chaos
    assert pk.resolve_pallas(default_config) is not None, \
        "chaos cells must actually exercise the kernels on CPU tier-1"
    base = chaos.make_cell("window_cb", str(tmp_path / "ck_a"), n=4096)
    chal = chaos.make_cell("window_cb", str(tmp_path / "ck_b"), n=4096)
    v = chaos.run_ab(base["factory"], chal["factory"],
                     chaos.default_kill("window_cb", "mid_epoch"),
                     base["read"], chal["read"])
    assert v["diff"] is None
    assert v["records"] > 0


# ---------------------------------------------------------------------------
# WF607: forced downgrades are named
# ---------------------------------------------------------------------------

def test_wf607_forced_generic_combiner_warns():
    src = (wf.Source_Builder(lambda: iter(()))
           .withOutputBatchSize(32).build())
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                    lambda a, b: a + b)
         .withCBWindows(8, 4).withKeyBy(lambda t: t["k"])
         .withMaxKeys(4).build())
    g = wf.PipeGraph("wf607", config=_cfg("1"))
    g.add_source(src).add(w).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    found = [d for d in g.check() if d.code == "WF607"]
    assert found and found[0].node == w.name
    assert "generic" in found[0].message


def test_wf607_forced_on_mesh_warns():
    """Mesh graphs keep the lax bodies (shard_map factories) — forcing
    the kernels there must be NAMED, not silently ignored."""
    from windflow_tpu.parallel import mesh as M
    mesh = M.make_mesh(8, data=2)
    kk = mesh.shape[M.KEY_AXIS]
    src = (wf.Source_Builder(lambda: iter(()))
           .withOutputBatchSize(16 * 8).build())
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                    lambda a, b: a + b)
         .withCBWindows(8, 4).withKeyBy(lambda t: t["k"])
         .withMaxKeys(4 * kk).withSumCombiner().build())
    g = wf.PipeGraph("wf607m", config=_cfg("1", mesh=mesh))
    g.add_source(src).add(w).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    found = [d for d in g.check() if d.code == "WF607"]
    assert found and "mesh" in found[0].message


def test_wf607_auto_mode_is_silent():
    src = (wf.Source_Builder(lambda: iter(()))
           .withOutputBatchSize(32).build())
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                    lambda a, b: a + b)
         .withCBWindows(8, 4).withKeyBy(lambda t: t["k"])
         .withMaxKeys(4).build())
    g = wf.PipeGraph("wf607b", config=_cfg("auto"))
    g.add_source(src).add(w).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    assert [d for d in g.check() if d.code == "WF607"] == []


# ---------------------------------------------------------------------------
# key-aligned mesh ingest: sharded dense reduce / stateful (satellite)
# ---------------------------------------------------------------------------

def _mesh_cfg(aligned, data=2, **kw):
    from windflow_tpu.parallel import mesh as M
    mesh = M.make_mesh(8, data=data)
    return mesh, dataclasses.replace(default_config, mesh=mesh,
                                     key_aligned_ingest=aligned, **kw)


def _run_mesh_reduce_max(aligned, data=2):
    from windflow_tpu.parallel import mesh as M
    mesh, cfg = _mesh_cfg(aligned, data)
    kk = mesh.shape[M.KEY_AXIS]
    cap, K = 16 * 8, 4 * kk
    rng = np.random.default_rng(5)
    records = [{"key": int(k), "value": -1.0 - float(v)}
               for k, v in zip(rng.integers(0, K, 6 * cap),
                               rng.integers(0, 97, 6 * cap))]
    outs = []
    src = (wf.Source_Builder(lambda: iter(records))
           .withOutputBatchSize(cap).build())
    red = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": jnp.maximum(a["key"], b["key"]),
                          "value": jnp.maximum(a["value"], b["value"])})
           .withKeyBy(lambda t: t["key"]).withMaxKeys(K)
           .withMonoidCombiner("max").build())
    g = wf.PipeGraph(f"amr_{aligned}", config=cfg)
    g.add_source(src).add(red).add_sink(
        wf.Sink_Builder(lambda t: outs.append(
            (int(t["key"]), float(t["value"])))
            if t is not None else None).build())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        g.run()
    agg = {}
    for k, v in outs:
        agg[k] = max(agg.get(k, -1e30), v)
    ici = (((g.stats().get("Shard") or {}).get("per_op") or {})
           .get(red.name) or {}).get("ici") or {}
    return agg, getattr(red, "_ingest_mode", None), ici


def test_aligned_mesh_dense_reduce_identical_and_collective_drops():
    """Sharded dense reduce under key-aligned ingest: per-key results
    identical to the data-sharded psum/pmax layout, the consumer is
    stamped aligned, and the ICI model stops charging the [K]-table
    collective (the aligned kind names the within-column gather)."""
    a, mode_a, ici_a = _run_mesh_reduce_max(True)
    b, mode_b, ici_b = _run_mesh_reduce_max(False)
    assert mode_a == "aligned" and mode_b is None
    assert a and a == b
    assert "key-aligned" in ici_a.get("collective", "")
    assert "psum" in ici_b.get("collective", "")
    assert ici_a["ici_bytes_per_tuple"] < ici_b["ici_bytes_per_tuple"]


def test_aligned_mesh_generic_reduce_identical():
    """Generic (undeclared) combiner on a declared key space: aligned
    ingest also kills the all_gather+fold table combine; totals
    identical per key."""
    from windflow_tpu.parallel import mesh as M

    def run(aligned):
        mesh, cfg = _mesh_cfg(aligned)
        kk = mesh.shape[M.KEY_AXIS]
        cap, K = 16 * 8, 4 * kk
        rng = np.random.default_rng(6)
        records = [{"key": int(k), "value": int(v)}
                   for k, v in zip(rng.integers(0, K, 6 * cap),
                                   rng.integers(0, 97, 6 * cap))]
        outs = []
        src = (wf.Source_Builder(lambda: iter(records))
               .withOutputBatchSize(cap).build())
        red = (wf.ReduceTPU_Builder(
                lambda a, b: {"key": a["key"],
                              "value": a["value"] + b["value"]})
               .withKeyBy(lambda t: t["key"]).withMaxKeys(K).build())
        g = wf.PipeGraph(f"agr_{aligned}", config=cfg)
        g.add_source(src).add(red).add_sink(
            wf.Sink_Builder(lambda t: outs.append(
                (int(t["key"]), int(t["value"])))
                if t is not None else None).build())
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            g.run()
        agg = defaultdict(int)
        for k, v in outs:
            agg[k] += v
        return dict(agg), getattr(red, "_ingest_mode", None)

    a, ma = run(True)
    b, mb = run(False)
    assert ma == "aligned" and mb is None
    assert a and a == b


@pytest.mark.parametrize("is_filter", [False, True])
def test_aligned_mesh_dense_stateful_identical(is_filter):
    """Dense-key stateful Map/Filter under key-aligned ingest: per-key
    output SEQUENCES identical to the data-sharded psum-merge layout —
    state evolution preserves per-key arrival order through the
    aligned placement."""
    from windflow_tpu.parallel import mesh as M

    def run(aligned):
        mesh, cfg = _mesh_cfg(aligned)
        kk = mesh.shape[M.KEY_AXIS]
        cap, S = 16 * 8, 4 * kk
        rng = np.random.default_rng(7 + is_filter)
        records = [{"k": int(k), "v": int(v)}
                   for k, v in zip(rng.integers(0, S, 5 * cap),
                                   rng.integers(0, 100, 5 * cap))]
        outs = []
        src = (wf.Source_Builder(lambda: iter(records))
               .withOutputBatchSize(cap).build())
        if is_filter:
            fn = lambda t, s: ((s + t["v"]) % 3 != 0, s + t["v"])
            op = (wf.FilterTPU_Builder(fn)
                  .withInitialState(jnp.int64(0))
                  .withKeyBy(lambda t: t["k"]).withNumKeySlots(S)
                  .withDenseKeys().build())
        else:
            fn = lambda t, s: ({"k": t["k"], "v": s + t["v"]},
                               s + t["v"])
            op = (wf.MapTPU_Builder(fn).withInitialState(jnp.int64(0))
                  .withKeyBy(lambda t: t["k"]).withNumKeySlots(S)
                  .withDenseKeys().build())
        g = wf.PipeGraph(f"ams_{aligned}_{is_filter}", config=cfg)
        g.add_source(src).add(op).add_sink(
            wf.Sink_Builder(lambda t: outs.append(
                (int(t["k"]), int(t["v"])))
                if t is not None else None).build())
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            g.run()
        per_key = defaultdict(list)
        for k, v in outs:
            per_key[k].append(v)
        return dict(per_key), getattr(op, "_ingest_mode", None)

    a, ma = run(True)
    b, mb = run(False)
    assert ma == "aligned" and mb is None
    assert a and a == b


def test_aligned_mesh_reduce_drops_out_of_range_keys():
    """Out-of-range keys clip onto an edge column host-side and mask
    out on device — dropped and counted exactly like the unaligned
    dense-table contract."""
    from windflow_tpu.parallel import mesh as M
    mesh, cfg = _mesh_cfg(True)
    kk = mesh.shape[M.KEY_AXIS]
    cap, K = 16 * 8, 4 * kk
    rng = np.random.default_rng(9)
    keys = rng.integers(-3, K + 3, 4 * cap)
    records = [{"key": int(k), "value": -1.0 - float(i % 7)}
               for i, k in enumerate(keys)]
    outs = []
    src = (wf.Source_Builder(lambda: iter(records))
           .withOutputBatchSize(cap).build())
    red = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": jnp.maximum(a["key"], b["key"]),
                          "value": jnp.maximum(a["value"], b["value"])})
           .withKeyBy(lambda t: t["key"]).withMaxKeys(K)
           .withMonoidCombiner("max").build())
    g = wf.PipeGraph("aoor", config=cfg)
    g.add_source(src).add(red).add_sink(
        wf.Sink_Builder(lambda t: outs.append(int(t["key"]))
                        if t is not None else None).build())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        g.run()
    n_oor = int(np.sum((keys < 0) | (keys >= K)))
    assert red.num_dropped_tuples() == n_oor
    assert outs and all(0 <= k < K for k in outs)
