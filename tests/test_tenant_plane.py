"""Tenant plane (monitoring/tenant_ledger.py): per-tenant attribution
across two co-resident graphs, the OVER_BUDGET enter/latch/clear state
machine, the tenancy advisor's golden plan, the OpenMetrics / postmortem
/ wf_tenant surfaces, the dashboard multi-app tenant-label merge, the
two-graph MonitoringThread lifecycle, and the off-path micro-assert.

The attribution honesty property is the plane's contract: the per-tenant
H2D/D2H byte totals are the SAME per-replica counters
``stats()["Bytes_H2D_total"]`` sums, so each tenant's roll-up must equal
its graph's own totals exactly, and the sum across tenants must
reconcile against the process staged-transfer delta
(``attributed.staged_fraction`` — the CI-gated >= 0.9 floor).  A ledger
that attributes less than it measures would hand PR 20's scheduler a
plan built on missing bytes."""

import dataclasses
import json
import os
import subprocess
import sys
import time

import pytest

import windflow_tpu as wf
from windflow_tpu.analysis import tenancy
from windflow_tpu.basic import default_config
from windflow_tpu.monitoring.health import OK, OVER_BUDGET
from windflow_tpu.monitoring.openmetrics import (parse_exposition,
                                                 render_openmetrics)
from windflow_tpu.monitoring.tenant_ledger import (CLEAR_AFTER,
                                                   ENTER_AFTER,
                                                   _TenantTrack,
                                                   default_ledger)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N = 4096
CAP = 256
KEYS = 8


def _graph(name, tenant, prefix, keys_fn, budget=0, n=N, cap=CAP,
           **cfg_kw):
    """One keyed source→map→window→sink graph with per-graph DISTINCT
    op names (the compile-ms prefix rule attributes by name)."""
    cfg = dataclasses.replace(default_config, tenant=tenant,
                              hbm_budget_bytes=budget, **cfg_kw)
    src = (wf.Source_Builder(
        lambda: iter({"key": keys_fn(i), "v": float(i)}
                     for i in range(n)))
        .withName(f"{prefix}_src").withOutputBatchSize(cap).build())
    m = (wf.MapTPU_Builder(lambda t: {"key": t["key"], "v": t["v"] * 2.0})
         .withName(f"{prefix}_map").build())
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"], lambda a, b: a + b)
         .withCBWindows(64, 32).withKeyBy(lambda t: t["key"])
         .withMaxKeys(KEYS).withName(f"{prefix}_win").build())
    snk = (wf.Sink_Builder(lambda r: None)
           .withName(f"{prefix}_snk").build())
    g = wf.PipeGraph(name, wf.ExecutionMode.DEFAULT, config=cfg)
    g.add_source(src).add(m).add(w).add_sink(snk)
    return g


def _drive(g):
    g.start()
    while not g.is_done():
        if not g.step():
            break
        g.health_tick()
    g.wait_end()
    g.health_tick()


@pytest.fixture(scope="module")
def two_tenants():
    """Two seeded graphs — Zipf-hot ('acme') + uniform ('blue') — in ONE
    process sharing the default ledger.  Returns the graphs, the
    process-level tenant section, and each graph's stats, all captured
    while the accounting epoch is intact."""
    led = default_ledger()
    led.reset()
    graphs = {}
    g = _graph("ten_acme_app", "acme", "za",
               lambda i: 0 if i % 4 else i % KEYS,
               budget=64 << 20)                    # generous: within
    _drive(g)
    graphs["acme"] = g
    g = _graph("ten_blue_app", "blue", "zb", lambda i: i % KEYS,
               budget=64 << 20)
    _drive(g)
    graphs["blue"] = g
    stats = {t: g.stats() for t, g in graphs.items()}
    return graphs, led.section(), stats


# ---------------------------------------------------------------------------
# attribution sums to the graphs' own totals + process reconciliation
# ---------------------------------------------------------------------------

def test_attribution_sums_to_graph_totals(two_tenants):
    graphs, sec, stats = two_tenants
    assert sec["enabled"]
    assert set(sec["tenants"]) >= {"acme", "blue"}
    for tenant, g in graphs.items():
        agg = sec["tenants"][tenant]
        st = stats[tenant]
        # the SAME per-replica counters stats() sums: exact equality
        assert agg["h2d_bytes"] == st["Bytes_H2D_total"], tenant
        assert agg["d2h_bytes"] == st["Bytes_D2H_total"], tenant
        assert agg["graphs"] == [g.name]
        assert agg["dispatches"] > 0
        assert agg["resident_state_bytes"] > 0, \
            "window operator state never attributed"
        # per-op rows carry this graph's distinct names only
        assert all(op.startswith(("za_", "zb_")) for op in agg["per_op"])
        assert agg["heaviest_op"] in agg["per_op"]
        assert agg["budget"]["pressure"] is not None
        assert not agg["budget"]["active"]


def test_staged_fraction_reconciles(two_tenants):
    _, sec, _ = two_tenants
    att = sec["attributed"]
    assert att["staged_bytes_process_total"] > 0
    # the CI floor (check_bench_keys): >= 90% of the process's staged
    # device bytes must attribute to tenants; the seeded two-graph run
    # attributes everything
    assert att["staged_fraction"] >= 0.9
    assert att["staged_bytes_tenants_total"] == \
        sum(t["h2d_bytes"] for t in sec["tenants"].values())


def test_stats_tenant_section_focuses_own_graph(two_tenants):
    graphs, _, stats = two_tenants
    for tenant, g in graphs.items():
        ten = stats[tenant]["Tenant"]
        assert ten["enabled"]
        assert ten["tenant"] == tenant          # the OpenMetrics label
        assert ten["graph"]["graph"] == g.name  # focused row
        # every graph's dump still carries the WHOLE process table: one
        # tenant's stats dump is enough for the advisor to plan across
        assert set(ten["tenants"]) >= {"acme", "blue"}


def test_dump_trace_carries_tenant(two_tenants, tmp_path):
    graphs, _, _ = two_tenants
    g = graphs["acme"]
    if g._recorder is None:
        pytest.skip("flight recorder off in this config")
    path = g.dump_trace(str(tmp_path / "t.json"))
    with open(path) as f:
        trace = json.load(f)
    assert trace["otherData"]["tenant"]["tenant"] == "acme"


# ---------------------------------------------------------------------------
# budget state machine: sustained entry, latch, hysteresis clear
# ---------------------------------------------------------------------------

def test_tenant_track_enter_latch_clear():
    tr = _TenantTrack("t", budget_bytes=100)
    # one over tick is a spike, not a verdict (sustained entry)
    tr.tick(150, "g", "op")
    assert not tr.active and tr.verdict is None
    for _ in range(ENTER_AFTER - 1):
        tr.tick(150, "g", "op")
    assert tr.active and tr.entered == 1
    v = tr.verdict
    assert v["state"] == "OVER_BUDGET"
    assert v["overage_bytes"] == 50
    assert v["heaviest_op"] == "op" and v["graph"] == "g"
    assert "100 B" in v["message"]
    # latch: still over, entered does not re-count
    tr.tick(160, "g", "op")
    assert tr.active and tr.entered == 1
    assert tr.verdict["hbm_bytes"] == 160    # verdict tracks the level
    # hysteresis: CLEAR_AFTER - 1 under-budget ticks must NOT clear
    for i in range(CLEAR_AFTER - 1):
        tr.tick(50, "g", "op")
        assert tr.active, f"cleared after {i + 1} OK tick(s)"
    tr.tick(50, "g", "op")
    assert not tr.active and tr.cleared == 1
    assert tr.verdict is None
    assert tr.last_verdict is not None       # forensics survive
    # re-enter counts a fresh violation (and needs sustaining again)
    tr.tick(150, "g", "op")
    assert not tr.active
    tr.tick(150, "g", "op")
    assert tr.active and tr.entered == 2


def test_tenant_track_no_budget_is_inert():
    tr = _TenantTrack("t", budget_bytes=0)
    for _ in range(10):
        tr.tick(1 << 40, "g", "op")
    assert not tr.active and tr.verdict is None and tr.entered == 0
    assert tr.budget_json(1 << 40)["pressure"] is None


def test_over_budget_paints_health_on_heaviest_op_and_latches():
    led = default_ledger()
    g = _graph("ten_ob_app", "ob_tenant", "ob", lambda i: i % KEYS,
               budget=1)                     # 1 B: every run violates
    _drive(g)
    # sustained entry at tick cadence (force bypasses the wall throttle)
    for _ in range(ENTER_AFTER):
        led.tick(tenant="ob_tenant", force=True)
    ten = g.stats()["Tenant"]
    bud = ten["tenants"]["ob_tenant"]["budget"]
    assert bud["active"] and bud["entered"] >= 1
    assert bud["pressure"] > 1.0
    v = bud["verdict"]
    assert v["state"] == "OVER_BUDGET"
    assert v["graph"] == g.name
    heaviest = v["heaviest_op"]
    assert heaviest in ten["tenants"]["ob_tenant"]["per_op"]
    # the health plane paints the verdict on the heaviest op ONLY —
    # one hungry operator does not paint the whole graph
    g.health_tick()
    h = g.stats()["Health"]
    assert h["graph_state"] == OVER_BUDGET
    for name, hv in h["verdicts"].items():
        if name == heaviest:
            assert hv["state"] == OVER_BUDGET
            assert hv["over_budget"]["message"] == v["message"]
        else:
            assert hv["state"] != OVER_BUDGET
            assert "over_budget" not in hv
    # the verdict latched past termination (frozen attribution rows)
    assert led.verdict_for(g.name) is not None


# ---------------------------------------------------------------------------
# off path: tenant_ledger=False never registers — one `is None` check
# ---------------------------------------------------------------------------

def test_off_path_never_registers():
    led = default_ledger()
    g = _graph("ten_off_app", "off_tenant", "off", lambda i: i % KEYS,
               tenant_ledger=False)
    _drive(g)
    assert g._tenant is None
    assert g.stats()["Tenant"] == {"enabled": False}
    assert "off_tenant" not in led.section()["tenants"]
    if g._health is not None:
        assert g._health.tenant is None
    # off-path budget (the health plane's stance): the disabled tenant
    # hook inside health_tick is ONE attribute check — with health off
    # too the whole tick must stay orders of magnitude under a sample
    g2 = _graph("ten_off2_app", "off_tenant", "of2", lambda i: i % KEYS,
                tenant_ledger=False, health_watchdog=False,
                flight_recorder=False)
    _drive(g2)
    t0 = time.perf_counter()
    for _ in range(10_000):
        g2.health_tick()
    per_call = (time.perf_counter() - t0) / 10_000
    assert per_call < 5e-6, \
        f"disabled health_tick costs {per_call * 1e6:.2f}us/call"


# ---------------------------------------------------------------------------
# tenancy advisor: rank order + the golden four-action plan
# ---------------------------------------------------------------------------

def _synthetic_section():
    """Three tenants: an over-budget hog (throttle + rescale + drain),
    a within-budget latency hot-spot (rebalance), and an idle one."""
    def agg(graphs, resident, per_op, heaviest, budget=None,
            latency_share=None, **kw):
        out = {"graphs": graphs, "dispatches": kw.get("dispatches", 10),
               "compile_ms": 1.0, "h2d_bytes": 1000,
               "h2d_logical_bytes": 1000, "d2h_bytes": 100,
               "resident_state_bytes": resident,
               "ici_bytes_per_tuple": 0.0, "latency_usec_total": 0.0,
               "latency_share": latency_share, "per_op": per_op,
               "heaviest_op": heaviest}
        if budget is not None:
            out["budget"] = budget
        return out

    hog_verdict = {"state": "OVER_BUDGET", "tenant": "hog",
                   "hbm_bytes": 250, "budget_bytes": 100,
                   "overage_bytes": 150, "graph": "hog_g",
                   "heaviest_op": "h_win", "message": "hog over"}
    return {
        "enabled": True,
        "tenants": {
            "hog": agg(["hog_g"], 250,
                       {"h_win": {"dispatches": 5,
                                  "resident_bytes": 200},
                        "h_map": {"dispatches": 5,
                                  "resident_bytes": 50}},
                       "h_win",
                       budget={"budget_bytes": 100, "hbm_bytes": 250,
                               "pressure": 2.5, "active": True,
                               "entered": 1, "cleared": 0,
                               "verdict": hog_verdict,
                               "last_verdict": hog_verdict}),
            "warm": agg(["warm_g"], 50,
                        {"w_map": {"dispatches": 8,
                                   "resident_bytes": 50}},
                        "w_map", latency_share=0.7,
                        budget={"budget_bytes": 1000, "hbm_bytes": 50,
                                "pressure": 0.05, "active": False,
                                "entered": 0, "cleared": 0,
                                "verdict": None, "last_verdict": None}),
            "idle": agg(["idle_g"], 10,
                        {"i_map": {"dispatches": 1,
                                   "resident_bytes": 10}},
                        "i_map"),
        },
        "attributed": {"staged_bytes_tenants_total": 3000,
                       "staged_bytes_process_total": 3000,
                       "staged_fraction": 1.0},
        "overhead": {"collects": 3, "collect_ms_total": 0.5,
                     "last_collect_ms": 0.1},
    }


def test_advisor_rank_order():
    ranked = tenancy.rank(_synthetic_section())
    # worst pressure first; budget-less tenants last
    assert [r["tenant"] for r in ranked] == ["hog", "warm", "idle"]
    assert ranked[0]["over_budget"] and ranked[0]["pressure"] == 2.5
    assert ranked[0]["heaviest_op_bytes"] == 200
    assert ranked[2]["pressure"] is None


def test_advisor_golden_plan():
    p = tenancy.plan(_synthetic_section())
    assert p["advisor"] == "tenancy/1"
    assert p["tenants_total"] == 3
    assert p["over_budget_tenants"] == ["hog"]
    assert p["worst_pressure"] == 2.5
    assert p["actionable"] == 2
    by_tenant = {t["tenant"]: t for t in p["tenants"]}
    # the golden plan: hog gets all three memory actions, in order
    kinds = [a["kind"] for a in by_tenant["hog"]["actions"]]
    assert kinds == ["throttle_admission", "rescale_tenant",
                     "drain_shards"]
    acts = {a["kind"]: a for a in by_tenant["hog"]["actions"]}
    assert acts["throttle_admission"]["factor"] == 3  # ceil(2.5)
    assert acts["rescale_tenant"]["shed_bytes"] == 150
    assert acts["drain_shards"]["op"] == "h_win"
    # warm: within budget but hot on latency — rebalance only
    kinds = [a["kind"] for a in by_tenant["warm"]["actions"]]
    assert kinds == ["rebalance_hot_tenant"]
    assert by_tenant["idle"]["actions"] == []
    json.dumps(p)    # the PR-20 wire contract is JSON-clean


# ---------------------------------------------------------------------------
# PR-20 scheduler stub: the plan contract is consumed + validated
# ---------------------------------------------------------------------------

def test_tenant_scheduler_consumes_plan():
    from windflow_tpu.serving import TenantScheduler
    sched = TenantScheduler()
    p = tenancy.plan(_synthetic_section())
    assert sched.ingest(p) == 4     # 3 hog actions + 1 warm action
    assert sched.plans_ingested == 1
    pending = sched.pending()
    assert [a["kind"] for a in pending] == [
        "throttle_admission", "rescale_tenant", "drain_shards",
        "rebalance_hot_tenant"]
    assert pending[0]["tenant"] == "hog"
    # the PR-20 seam: pops in order, records applied=False
    first = sched.apply_next()
    assert first["kind"] == "throttle_admission"
    assert first["applied"] is False
    assert len(sched.pending()) == 3
    assert sched.section()["timeline"] == [first]


def test_tenant_scheduler_rejects_contract_drift():
    from windflow_tpu.serving import TenantScheduler
    sched = TenantScheduler()
    with pytest.raises(ValueError, match="tenancy/1"):
        sched.ingest({"advisor": "tenancy/2", "tenants": []})
    with pytest.raises(ValueError, match="unknown action kind"):
        sched.ingest({"advisor": "tenancy/1", "tenants": [
            {"tenant": "x", "actions": [{"kind": "evict_tenant"}]}]})
    with pytest.raises(ValueError, match="missing required field"):
        sched.ingest({"advisor": "tenancy/1", "tenants": [
            {"tenant": "x",
             "actions": [{"kind": "throttle_admission"}]}]})
    assert sched.rejected_plans == 3 and not sched.pending()


# ---------------------------------------------------------------------------
# OpenMetrics: wf_tenant_* families round-trip the same numbers; the
# tenant base label rides every family; label escaping holds
# ---------------------------------------------------------------------------

def _samples(fams, name):
    return fams[name]["samples"]


def test_openmetrics_tenant_families_round_trip(two_tenants):
    _, _, stats = two_tenants
    st = stats["acme"]
    fams = parse_exposition(render_openmetrics(st))
    ten = st["Tenant"]
    # per-tenant families carry the SAME numbers the section reports
    for tenant, agg in ten["tenants"].items():
        rows = {lab["tenant"]: val for _, lab, val
                in _samples(fams, "wf_tenant_hbm_bytes")}
        assert rows[tenant] == agg["resident_state_bytes"]
        rows = {lab["tenant"]: val for _, lab, val
                in _samples(fams, "wf_tenant_dispatches_total")}
        assert rows[tenant] == agg["dispatches"]
        rows = {lab["tenant"]: val for _, lab, val
                in _samples(fams, "wf_tenant_h2d_bytes_total")}
        assert rows[tenant] == agg["h2d_bytes"]
        rows = {lab["tenant"]: val for _, lab, val
                in _samples(fams, "wf_tenant_budget_pressure")}
        assert rows[tenant] == pytest.approx(
            agg["budget"]["pressure"], abs=1e-4)
    frac = [(lab, val) for _, lab, val in _samples(
        fams, "wf_tenant_attributed_staged_fraction")]
    assert frac and frac[0][1] == pytest.approx(
        ten["attributed"]["staged_fraction"], abs=1e-4)
    # the tenant base label rides every per-operator family: the
    # disambiguator for the dashboard's merged multi-app exposition
    for _, lab, _ in _samples(fams, "wf_operator_outputs_total"):
        assert lab["tenant"] == "acme"


def test_openmetrics_over_budget_enum_state():
    g = _graph("ten_om_ob_app", "om_ob_tenant", "oo",
               lambda i: i % KEYS, budget=1)
    _drive(g)
    for _ in range(ENTER_AFTER):
        default_ledger().tick(tenant="om_ob_tenant", force=True)
    g.health_tick()
    fams = parse_exposition(render_openmetrics(g.stats()))
    health = {(lab["operator"], lab["state"]): val for _, lab, val
              in _samples(fams, "wf_operator_health")}
    assert any(state == "over_budget" and val == 1
               for (_, state), val in health.items())
    over = {lab["tenant"]: val for _, lab, val
            in _samples(fams, "wf_tenant_over_budget")}
    assert over["om_ob_tenant"] == 1


def test_openmetrics_tenant_label_escaping():
    nasty = 'we"ird\\ten\nant'
    g = _graph("ten_esc_app", nasty, "esc", lambda i: i % KEYS)
    _drive(g)
    fams = parse_exposition(render_openmetrics(g.stats()))
    tenants = {lab["tenant"] for _, lab, _
               in _samples(fams, "wf_tenant_hbm_bytes")}
    assert nasty in tenants     # escaped on the wire, intact parsed


# ---------------------------------------------------------------------------
# dashboard /metrics: two same-topology apps merge into ONE strict-valid
# exposition, kept apart by the app/tenant labels (the collision fix)
# ---------------------------------------------------------------------------

def test_dashboard_metrics_two_same_topology_apps():
    import urllib.request
    from windflow_tpu.monitoring import DashboardServer
    server = DashboardServer(tcp_port=0, http_port=0).start()
    try:
        for tenant in ("twin_a", "twin_b"):
            # SAME app name, SAME op names — only the tenant differs
            g = _graph("twin_app", tenant, "tw", lambda i: i % KEYS,
                       tracing_enabled=True,
                       dashboard_host="127.0.0.1",
                       dashboard_port=server.tcp_port, n=1024)
            _drive(g)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.http_port}/metrics",
                timeout=5) as r:
            assert r.status == 200
            text = r.read().decode()
        fams = parse_exposition(text)   # strict: one TYPE per family
        pairs = {(lab.get("app"), lab.get("tenant"))
                 for _, lab, _ in _samples(fams,
                                           "wf_operator_outputs_total")}
        # identical topology + identical app name: without the tenant
        # label these samples would collide indistinguishably
        assert {("twin_app", "twin_a"), ("twin_app", "twin_b")} <= pairs
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# monitor lifecycle: two graphs in one process each assert END_APP;
# abnormal termination carries the Aborted marker per graph
# ---------------------------------------------------------------------------

def test_monitor_two_graphs_end_app_and_abort():
    from windflow_tpu.monitoring import DashboardServer
    server = DashboardServer(tcp_port=0, http_port=0).start()
    try:
        ok = _graph("mt_ok_app", "mt_ok", "mo", lambda i: i % KEYS,
                    tracing_enabled=True, dashboard_host="127.0.0.1",
                    dashboard_port=server.tcp_port, n=1024)
        _drive(ok)

        def boom(t):
            if t["v"] > 500:
                raise ValueError("seeded operator crash")
            return {"key": t["key"], "v": t["v"]}
        cfg = dataclasses.replace(
            default_config, tenant="mt_bad", tracing_enabled=True,
            dashboard_host="127.0.0.1", dashboard_port=server.tcp_port)
        src = (wf.Source_Builder(
            lambda: iter({"key": i % KEYS, "v": float(i)}
                         for i in range(3000)))
            .withName("mb_src").withOutputBatchSize(CAP).build())
        m = wf.Map_Builder(boom).withName("mb_map").build()
        snk = (wf.Sink_Builder(lambda r: None)
               .withName("mb_snk").build())
        bad = wf.PipeGraph("mt_bad_app", wf.ExecutionMode.DEFAULT,
                           config=cfg)
        bad.add_source(src).add(m).add_sink(snk)
        with pytest.raises(ValueError, match="seeded operator crash"):
            bad.run()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            records = {a.name: a for a in server.apps.values()}
            if {"mt_ok_app", "mt_bad_app"} <= set(records) \
                    and all(r.ended for r in records.values()):
                break
            time.sleep(0.05)
        records = {a.name: a for a in server.apps.values()}
        assert {"mt_ok_app", "mt_bad_app"} <= set(records)
        # END_APP landed per graph — neither stays "live" forever
        assert records["mt_ok_app"].ended
        assert records["mt_bad_app"].ended
        # the abnormal path carries the Aborted marker; the normal one
        # does not
        assert records["mt_bad_app"].reports[-1].get("Aborted") is True
        assert not records["mt_ok_app"].reports[-1].get("Aborted")
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# wf_tenant CLI: rank/plan render, --check budget gate, exit codes
# ---------------------------------------------------------------------------

def _wf_tenant(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_tenant.py"),
         *args], capture_output=True, text=True, timeout=60)


def test_wf_tenant_on_real_stats(two_tenants, tmp_path):
    _, _, stats = two_tenants
    path = tmp_path / "stats.json"
    path.write_text(json.dumps(stats["acme"]))
    # both tenants within budget: --check passes
    r = _wf_tenant("--check", "--stats", str(path))
    assert r.returncode == 0, r.stderr + r.stdout
    assert "OK" in r.stdout
    # render names both tenants with budget bars
    r = _wf_tenant("--stats", str(path))
    assert "acme" in r.stdout and "blue" in r.stdout
    r = _wf_tenant("--json", "--stats", str(path))
    assert json.loads(r.stdout)["advisor"] == "tenancy/1"


def test_wf_tenant_check_gates_over_budget(tmp_path):
    path = tmp_path / "tenant.json"
    path.write_text(json.dumps(_synthetic_section()))  # bare section
    r = _wf_tenant("--check", "--stats", str(path))
    assert r.returncode == 1
    assert "OVER BUDGET" in r.stdout and "hog" in r.stdout
    # the plan run exits 0 (actionable) and names the golden actions
    r = _wf_tenant("--stats", str(path))
    assert r.returncode == 0
    for needle in ("throttle_admission", "rescale_tenant",
                   "drain_shards", "rebalance_hot_tenant"):
        assert needle in r.stdout, needle


def test_wf_tenant_check_gates_attribution_gap(tmp_path):
    sec = _synthetic_section()
    for name in list(sec["tenants"]):
        sec["tenants"][name].pop("budget", None)   # nothing over budget
    sec["attributed"]["staged_fraction"] = 0.5
    path = tmp_path / "tenant.json"
    path.write_text(json.dumps(sec))
    r = _wf_tenant("--check", "--stats", str(path))
    assert r.returncode == 1
    assert "ATTRIBUTION GAP" in r.stdout
    # the floor is tunable: --min-fraction under the reported value passes
    r = _wf_tenant("--check", "--min-fraction", "0.4",
                   "--stats", str(path))
    assert r.returncode == 0


def test_wf_tenant_rejects_missing_section(tmp_path):
    path = tmp_path / "bare.json"
    path.write_text(json.dumps({"PipeGraph_name": "x"}))
    r = _wf_tenant("--stats", str(path))
    assert r.returncode == 2
    assert "no enabled 'Tenant' section" in r.stderr


# ---------------------------------------------------------------------------
# postmortem: tenant.json rides the bundle, wf_doctor renders +
# validates it, corrupt sections reject, old bundles stay valid
# ---------------------------------------------------------------------------

def _wf_doctor(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_doctor.py"),
         *args], capture_output=True, text=True, timeout=60)


@pytest.fixture()
def tenant_bundle(tmp_path):
    default_ledger().reset()   # isolate: the bundle snapshots the
    g = _graph("ten_pm_app", "pm_tenant", "pm", lambda i: i % KEYS,
               budget=1, log_dir=str(tmp_path))
    _drive(g)
    for _ in range(ENTER_AFTER):
        default_ledger().tick(tenant="pm_tenant", force=True)
    bundle = g.dump_postmortem(str(tmp_path / "pm"), reason="manual")
    with open(os.path.join(bundle, "manifest.json")) as f:
        manifest = json.load(f)
    assert "tenant.json" in manifest["files"]
    return bundle


def test_postmortem_tenant_roundtrips_wf_doctor(tenant_bundle):
    r = _wf_doctor("--check", tenant_bundle)
    assert r.returncode == 0, r.stderr
    # the jax-free render names the worst-pressure tenant + the verdict
    r = _wf_doctor(tenant_bundle)
    assert r.returncode == 0, r.stderr
    assert "tenancy:" in r.stdout
    assert "pm_tenant" in r.stdout
    assert "OVER BUDGET (latched)" in r.stdout


def test_wf_doctor_rejects_corrupt_tenant_section(tenant_bundle):
    tp = os.path.join(tenant_bundle, "tenant.json")
    with open(tp) as f:
        ten = json.load(f)
    ten["tenants"]["pm_tenant"]["budget"]["verdict"]["state"] = "HUNGRY"
    with open(tp, "w") as f:
        json.dump(ten, f)
    r = _wf_doctor("--check", tenant_bundle)
    assert r.returncode == 1
    assert "OVER_BUDGET" in r.stderr
    # structurally wrong type rejects too
    ten["tenants"] = ["not", "a", "mapping"]
    with open(tp, "w") as f:
        json.dump(ten, f)
    r = _wf_doctor("--check", tenant_bundle)
    assert r.returncode == 1
    assert "tenants must be an object" in r.stderr


def test_wf_doctor_accepts_pre_tenant_bundle(tenant_bundle):
    # a bundle written before the tenant plane existed has no
    # tenant.json and no manifest entry — it must still validate
    mp = os.path.join(tenant_bundle, "manifest.json")
    with open(mp) as f:
        manifest = json.load(f)
    manifest["files"] = [n for n in manifest["files"]
                         if n != "tenant.json"]
    with open(mp, "w") as f:
        json.dump(manifest, f)
    os.remove(os.path.join(tenant_bundle, "tenant.json"))
    r = _wf_doctor("--check", tenant_bundle)
    assert r.returncode == 0, r.stderr
