"""Reshard/failover executor (windflow_tpu/serving): the state machine
that closes the shard-plane loop — BACKPRESSURED/imbalance triggers
drive move_keys live through the quiesce→re-place→resume barrier (keyed
state moving with the keys), split_hot_key becomes a pre-aggregating
partial combine at the staging boundary, and when no plan helps,
admission control throttles the sources.  Everything runs on a
simulated (JAX_PLATFORMS=cpu) box; correctness is always asserted
record-exactly against a pure-Python oracle — a reshard that loses or
double-counts one record is a failed reshard, whatever its counters
say."""

import dataclasses

import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import stable_hash
from windflow_tpu.durability.checkpoint import keyed_emitters_into

N_SHARDS = 3


def _cfg(**kw):
    cfg = dataclasses.replace(wf.default_config)
    cfg.reshard_executor = True
    cfg.reshard_check_sweeps = 4
    cfg.reshard_trigger_ticks = 2
    cfg.reshard_ok_ticks = 2
    cfg.reshard_imbalance_threshold = 1.6
    # determinism: wall-clock punctuation moves batch boundaries
    cfg.punctuation_interval_usec = 10 ** 12
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


def _colocated_keys(n_shards: int, shard: int, want: int = 2,
                    upto: int = 200) -> list:
    """Keys that the host keyby placement (stable_hash % n) lands on
    ``shard`` — the seeded skew every test builds from."""
    out = [k for k in range(upto)
           if stable_hash(k) % n_shards == shard]
    assert len(out) >= want
    return out[:want]


def _run_reduce_graph(stream_fn, cfg, parallelism=N_SHARDS):
    """Host keyed Reduce graph: per-key running (count, sum) states —
    the executor's move target whose state must re-home with the key."""
    def red_fn(item, state):
        state["key"] = item["key"]
        state["n"] = state.get("n", 0) + 1
        state["s"] = state.get("s", 0.0) + item["value"]

    outs = []
    g = wf.PipeGraph("reshard_t", config=cfg)
    src = (wf.Source_Builder(stream_fn)
           .withOutputBatchSize(256).build())
    red = (wf.Reduce_Builder(red_fn, dict)
           .withKeyBy(lambda t: t["key"])
           .withParallelism(parallelism).withName("red").build())
    snk = wf.Sink_Builder(
        lambda r: outs.append(dict(r)) if r is not None else None).build()
    g.add_source(src).add(red).add_sink(snk)
    g.run()
    return g, red, outs


def _assert_reduce_exact(outs, stream_records):
    per = {}
    for t in stream_records:
        k = t["key"]
        n, s = per.get(k, (0, 0.0))
        per[k] = (n + 1, s + t["value"])
    final = {r["key"]: (r["n"], r["s"]) for r in outs}
    for k, want in per.items():
        assert final.get(k) == want, (k, final.get(k), want)


# ---------------------------------------------------------------------------
# off-path + section plumbing
# ---------------------------------------------------------------------------

def test_executor_off_by_default():
    """Config.reshard_executor defaults OFF (the executor mutates
    routing — opt-in, unlike the observe-only planes): no plane is
    built and the stats section reports disabled."""
    cfg = dataclasses.replace(wf.default_config)
    assert cfg.reshard_executor is False
    got = []
    g = wf.PipeGraph("reshard_off", config=cfg)
    src = wf.Source_Builder(
        lambda: iter([{"key": i % 4, "value": 1.0} for i in range(512)])
    ).withOutputBatchSize(128).build()
    g.add_source(src).add_sink(wf.Sink_Builder(
        lambda r: got.append(r) if r is not None else None).build())
    g.run()
    assert g._reshard is None
    assert g.stats()["Reshard"] == {"enabled": False}
    assert len(got) == 512


# ---------------------------------------------------------------------------
# BACKPRESSURED/imbalance -> move_keys -> recovered
# ---------------------------------------------------------------------------

def test_move_keys_separates_colocated_warm_keys():
    """Two warm keys (25% each) hash-colocated on one shard: the
    executor must trigger, apply a move_keys plan through the quiesce
    barrier (re-homing the Reduce per-key state), and reach RECOVERED —
    with every per-key aggregate exact."""
    h1, h2 = _colocated_keys(N_SHARDS, 0)
    N, KEYS = 24000, 12
    records = []
    for i in range(N):
        r = i % 20
        k = h1 if r < 5 else (h2 if r < 10 else (i % KEYS))
        records.append({"key": k, "value": float(i % 97)})

    g, red, outs = _run_reduce_graph(lambda: iter(records), _cfg())
    rs = g.stats()["Reshard"]
    assert rs["enabled"] and rs["plans_applied"] >= 1
    assert rs["keys_moved"] >= 1
    events = [e["event"] for e in rs["timeline"]]
    assert "move_keys" in events
    assert "recovered" in events
    assert rs["recovery_ms"] is not None and rs["quiesce_ms"] is not None
    # the override actually landed on the routing plane
    ovs = [getattr(em, "_override", None)
           for em in keyed_emitters_into(g, red)]
    assert any(ovs), "no emitter carries the move override"
    _assert_reduce_exact(outs, records)


def test_zipf_shift_mid_run_migration():
    """The millions-of-users regression: the hot pair MIGRATES mid-run
    (phase 1 skews shard 0, phase 2 skews shard 1) and the executor
    re-plans live — at least two applied plans, no process restart, and
    the post-shift plan recovers with per-key exactness intact."""
    p1 = _colocated_keys(N_SHARDS, 0)
    p2 = _colocated_keys(N_SHARDS, 1)
    N, KEYS = 40000, 12
    records = []
    for i in range(N):
        hot = p1 if i < N // 2 else p2
        r = i % 20
        k = hot[0] if r < 5 else (hot[1] if r < 10 else (i % KEYS))
        records.append({"key": k, "value": float(i % 89)})

    g, red, outs = _run_reduce_graph(lambda: iter(records), _cfg())
    rs = g.stats()["Reshard"]
    assert rs["plans_applied"] >= 2, rs["timeline"]
    moves = [e for e in rs["timeline"] if e["event"] == "move_keys"]
    assert len(moves) >= 2
    recovered = [e for e in rs["timeline"] if e["event"] == "recovered"]
    assert recovered, "no recovery after the live migrations"
    # throughput recovered: the graph ends un-throttled
    assert rs["admission_factor"] == 1.0
    _assert_reduce_exact(outs, records)


# ---------------------------------------------------------------------------
# hot key -> split -> pre-aggregating partial combine
# ---------------------------------------------------------------------------

def test_split_hot_key_partial_combine_on_monoid_reduce():
    """A 60% hot key exceeds any shard's fair share — routing cannot
    fix it; the executor must engage the split: a pre-aggregating
    partial combine at the keyed staging boundary, absorbing hot-key
    tuples into folded partials (preagg_folds) while the final per-key
    aggregate stays exact (max monoid: idempotent, bit-exact)."""
    N, KEYS, HOT = 24000, 8, 5

    def key_of(i):
        return HOT if i % 10 < 6 else (i % KEYS)

    def v_of(i):
        return -2.0 - ((i * 29) % 83) / 7.0

    outs = []
    g = wf.PipeGraph("split_t", config=_cfg(
        reshard_imbalance_threshold=1.25))
    src = wf.Source_Builder(
        lambda: iter({"key": key_of(i), "v": v_of(i)}
                     for i in range(N))).withOutputBatchSize(256).build()
    red = (wf.ReduceTPU_Builder(
        lambda a, b: {"key": jnp.maximum(a["key"], b["key"]),
                      "v": jnp.maximum(a["v"], b["v"])})
        .withKeyBy(lambda t: t["key"]).withMonoidCombiner("max")
        .withParallelism(2).withMaxKeys(KEYS).withName("dred").build())
    snk = wf.Sink_Builder(
        lambda r: outs.append({"key": int(r["key"]), "v": float(r["v"])})
        if r is not None else None).build()
    g.add_source(src).add(red).add_sink(snk)
    g.run()

    rs = g.stats()["Reshard"]
    assert rs["splits_applied"] >= 1, rs["timeline"]
    assert rs["preagg_folds"] > 0
    assert "split_hot_key" in [e["event"] for e in rs["timeline"]]
    per = {}
    for i in range(N):
        k = key_of(i)
        per[k] = max(per.get(k, -1e18), v_of(i))
    got = {}
    for r in outs:
        got[r["key"]] = max(got.get(r["key"], -1e18), r["v"])
    for k, v in per.items():
        assert abs(got[k] - v) < 1e-6, (k, got.get(k), v)


# ---------------------------------------------------------------------------
# no plan helps -> admission control at the source
# ---------------------------------------------------------------------------

def test_no_plan_admission_control_degrades_and_holds_exactness():
    """A dominant hot key on a HOST Reduce (no associative record
    combiner, so the split tier is unavailable) leaves the executor no
    applicable plan: it must degrade admission at the source (factor
    halves, throttles counted) instead of thrashing moves — and the
    stream still completes with exact per-key aggregates."""
    N, KEYS, HOT = 20000, 8, 5
    records = [{"key": HOT if i % 10 < 6 else (i % KEYS),
                "value": float(i % 53)} for i in range(N)]
    g, red, outs = _run_reduce_graph(
        lambda: iter(records), _cfg(reshard_imbalance_threshold=1.25))
    rs = g.stats()["Reshard"]
    assert rs["admission_throttles"] >= 1, rs["timeline"]
    admissions = [e for e in rs["timeline"] if e["event"] == "admission"]
    assert any("throttled" in e["detail"] for e in admissions)
    _assert_reduce_exact(outs, records)


# ---------------------------------------------------------------------------
# surfaces: OpenMetrics + postmortem/wf_doctor
# ---------------------------------------------------------------------------

def test_reshard_openmetrics_families_and_postmortem(tmp_path):
    """The executor's counters ship as wf_reshard_* OpenMetrics
    families (strict-parser clean) and as the postmortem bundle's
    reshard.json, which wf_doctor renders and validates jax-free."""
    import json
    import os
    import subprocess
    import sys
    h1, h2 = _colocated_keys(N_SHARDS, 0)
    N = 16000
    records = []
    for i in range(N):
        r = i % 20
        k = h1 if r < 5 else (h2 if r < 10 else (i % 12))
        records.append({"key": k, "value": 1.0})
    g, red, outs = _run_reduce_graph(lambda: iter(records), _cfg())
    stats = g.stats()
    assert stats["Reshard"]["enabled"]

    from windflow_tpu.monitoring.openmetrics import (parse_exposition,
                                                     render_openmetrics)
    text = render_openmetrics(stats)
    fams = parse_exposition(text)
    for fam in ("wf_reshard_plans_applied_total",
                "wf_reshard_keys_moved_total",
                "wf_reshard_admission_factor"):
        assert fam in fams, fam

    d = g.dump_postmortem(str(tmp_path / "bundle"), reason="test")
    with open(os.path.join(d, "reshard.json")) as f:
        rj = json.load(f)
    assert rj["enabled"] and isinstance(rj["timeline"], list)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "wf_doctor.py"),
         d, "--check"], capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    render = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "wf_doctor.py"), d],
        capture_output=True, text=True)
    assert "Reshard" in render.stdout, render.stdout


# ---------------------------------------------------------------------------
# the state machine itself: health-BACKPRESSURED drives the transitions
# ---------------------------------------------------------------------------

def test_state_machine_backpressured_to_move_keys_to_recovered():
    """The executor's own transitions, driven by synthetic health
    verdicts (the trigger the ISSUE names): BACKPRESSURED ticks confirm
    the trigger, the plan's move applies through the quiesce barrier,
    and sustained OK closes the loop at RECOVERED→OK."""
    import windflow_tpu.serving.executor as ex

    records = [{"key": i % 6, "value": 1.0} for i in range(6000)]
    g, red, outs = _run_reduce_graph(
        lambda: iter(records),
        # cadence far beyond the run: we tick by hand
        _cfg(reshard_check_sweeps=10 ** 9))
    x = g._reshard
    assert x is not None and "red" in x._targets
    move = {"kind": "move_keys",
            "moves": [{"key": 0, "from_shard": 0, "to_shard": 1,
                       "est_tuples": 10}]}
    bp = {"red": {"state": "BACKPRESSURED"}}
    plan_entry = {"op": "red", "loads": [100, 10, 10],
                  "imbalance_ratio": 2.5, "hot_keys": [],
                  "actions": [move]}
    x._health_verdicts = lambda: bp
    x._plan = lambda: {"ops": [plan_entry]}
    tr = x._tracks["red"]
    x.tick()
    assert tr.state == ex.E_TRIGGERED
    x.tick()
    assert tr.state == ex.E_RECOVERING     # trigger_ticks=2 → applied
    assert x.plans_applied == 1 and x.keys_moved == 1
    x._health_verdicts = lambda: {"red": {"state": "OK"}}
    # a finished graph's delta windows carry no signal (tri-state None
    # holds position by design) — stub a balanced window so the
    # recovery half of the machine is what this test exercises
    x._delta_imbalance = lambda name, loads: 1.0
    x.tick()
    x.tick()
    assert tr.state == ex.E_OK
    assert [e["event"] for e in x.timeline][:3] == [
        "triggered", "move_keys", "recovered"]


# ---------------------------------------------------------------------------
# scale-down on sustained OK
# ---------------------------------------------------------------------------

def test_scale_down_consolidates_on_sustained_ok():
    """A balanced stream with scale-down enabled: after the sustained-OK
    window the executor drains the least-loaded shard's known keys (or
    records the drain candidate when none are known) — the
    capacity-shrink half whose realization is a rescale restore."""
    N, KEYS = 20000, 12
    records = [{"key": i % KEYS, "value": 1.0} for i in range(N)]
    g, red, outs = _run_reduce_graph(
        lambda: iter(records),
        _cfg(reshard_scale_down_ticks=3, reshard_check_sweeps=2))
    rs = g.stats()["Reshard"]
    assert rs["scale_down_events"] >= 1, rs["timeline"]
    assert "scale_down" in [e["event"] for e in rs["timeline"]]
    _assert_reduce_exact(outs, records)
