"""Worker process for the two-process DCN test (SURVEY §5.8).

Each process joins a real ``jax.distributed`` job (Gloo CPU collectives,
TCP coordinator — the CPU stand-in for DCN), exposes 4 virtual devices,
builds the multi-host ``(data, key)`` mesh with host boundaries on the key
axis, stages its OWN half of the input through ``stage_local``, and runs
the sharded keyed reduce and the key-sharded FFAT window step across both
processes.  Every process verifies the full result against a locally
computed oracle; exit code 0 = all assertions held.

Run by ``tests/test_multihost.py::test_two_process_dcn_reduce_and_ffat``;
usable standalone:  python _multihost_worker.py <proc_id> <nproc> <port>
"""

import os
import sys


def main() -> None:
    proc_id, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from windflow_tpu.parallel.multihost import (initialize,
                                                 make_multihost_mesh,
                                                 stage_local)
    initialize(coordinator_address=f"127.0.0.1:{port}",
               num_processes=nproc, process_id=proc_id)
    assert jax.process_count() == nproc, jax.process_count()

    import numpy as np

    import jax.numpy as jnp
    from jax.experimental.multihost_utils import process_allgather

    from windflow_tpu.batch import HostBatch
    from windflow_tpu.parallel import mesh as meshmod

    mesh = make_multihost_mesh(local_data=2)
    assert mesh.shape == {"data": 2, "key": 2 * nproc}, mesh.shape
    # host boundaries on the key axis: this process's devices own whole
    # key columns (the data-axis all_gather stays inside one host)
    for col in range(mesh.devices.shape[1]):
        owners = {d.process_index for d in mesh.devices[:, col]}
        assert len(owners) == 1, (col, owners)

    # -- keyed reduce: each process stages only the lanes IT ingested ------
    K, CAP = 16, 256
    rng = np.random.default_rng(5)
    keys = rng.integers(0, K, CAP)             # full input derivable by all
    vals = rng.integers(0, 1000, CAP).astype(np.float64)
    lo, hi = proc_id * CAP // nproc, (proc_id + 1) * CAP // nproc
    hb = HostBatch([{"k": int(k), "v": float(v)}
                    for k, v in zip(keys[lo:hi], vals[lo:hi])],
                   list(range(lo, hi)), 0)
    db = stage_local(hb, CAP, mesh)
    fn = meshmod.make_sharded_keyed_reduce(
        mesh, CAP, K, lambda a, b: {"k": a["k"], "v": a["v"] + b["v"]},
        key_fn=lambda t: t["k"], use_psum=False)
    table, has = fn(db.payload, db.valid)
    expected = np.zeros(K)
    for k, v in zip(keys, vals):
        expected[k] += v
    got = np.asarray(table["v"])      # replicated output: readable anywhere
    np.testing.assert_allclose(got, expected, rtol=1e-6)
    assert bool(np.asarray(has).all())
    print(f"proc {proc_id}: keyed reduce across {nproc} processes OK",
          flush=True)

    # -- key-sharded FFAT CB windows across the process boundary -----------
    Kf, CAPf, Pn, R, D = 8, 64, 4, 4, 1
    lift = lambda t: t["v"]
    comb = lambda a, b: a + b
    key_fn = lambda t: t["k"]
    step = meshmod.make_sharded_ffat_step(mesh, CAPf, Kf, Pn, R, D,
                                          lift, comb, key_fn)
    state = meshmod.make_sharded_ffat_state(jnp.zeros(()), Kf, R, mesh)

    from windflow_tpu.windows.ffat_kernels import (make_ffat_state,
                                                   make_ffat_step)
    ref_step = jax.jit(make_ffat_step(CAPf, Kf, Pn, R, D, lift, comb,
                                      key_fn))
    ref_state = make_ffat_state(jnp.zeros(()), Kf, R)

    from jax.sharding import NamedSharding, PartitionSpec
    bsh = NamedSharding(mesh, PartitionSpec(meshmod.DATA_AXIS))

    def global_put(a):
        # data-sharded global array; every process derives the full input
        # (same seed) and contributes each device's slice
        return jax.make_array_from_callback(
            a.shape, bsh, lambda idx: a[idx])

    rng2 = np.random.default_rng(7)
    got_w, exp_w = {}, {}
    for _ in range(6):
        k_np = rng2.integers(0, Kf, CAPf).astype(np.int32)
        v_np = rng2.integers(0, 100, CAPf).astype(np.float32)  # exact sums
        ts_np = np.arange(CAPf, dtype=np.int64)
        ok_np = np.ones(CAPf, bool)
        payload = {"k": global_put(k_np), "v": global_put(v_np)}
        state, out, fired, _ = step(state, payload, global_put(ts_np),
                                    global_put(ok_np))
        # reference single-chip run on local, unsharded arrays
        ref_payload = {"k": jnp.asarray(k_np), "v": jnp.asarray(v_np)}
        ref_state, rout, rfired, _ = ref_step(
            ref_state, ref_payload, jnp.asarray(ts_np), jnp.asarray(ok_np))
        fired_np = process_allgather(fired, tiled=True)
        out_np = {kk: process_allgather(v, tiled=True)
                  for kk, v in out.items()}
        for o, f, dst in ((out_np, fired_np, got_w),
                          ({kk: np.asarray(v) for kk, v in rout.items()},
                           np.asarray(rfired), exp_w)):
            for i in np.nonzero(f)[0]:
                dst[(int(o["key"][i]), int(o["wid"][i]))] = \
                    float(o["value"][i])
    assert len(exp_w) > 0
    assert got_w == exp_w, (len(got_w), len(exp_w))
    print(f"proc {proc_id}: FFAT windows across {nproc} processes OK",
          flush=True)
    print(f"proc {proc_id}: DCN_WORKER_OK", flush=True)


if __name__ == "__main__":
    main()
