"""Worker process for the two-process DCN test (SURVEY §5.8).

Each process joins a real ``jax.distributed`` job (Gloo CPU collectives,
TCP coordinator — the CPU stand-in for DCN), exposes 4 virtual devices,
builds the multi-host ``(data, key)`` mesh with host boundaries on the key
axis, stages its OWN half of the input through ``stage_local``, and runs
the sharded keyed reduce and the key-sharded FFAT window step across both
processes.  Every process verifies the full result against a locally
computed oracle; exit code 0 = all assertions held.

Run by ``tests/test_multihost.py::test_two_process_dcn_reduce_and_ffat``;
usable standalone:  python _multihost_worker.py <proc_id> <nproc> <port>
"""

import os
import sys


def main() -> None:
    proc_id, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from windflow_tpu.parallel.multihost import (initialize,
                                                 make_multihost_mesh,
                                                 stage_local)
    initialize(coordinator_address=f"127.0.0.1:{port}",
               num_processes=nproc, process_id=proc_id)
    assert jax.process_count() == nproc, jax.process_count()

    import numpy as np

    import jax.numpy as jnp
    from jax.experimental.multihost_utils import process_allgather

    from windflow_tpu.batch import HostBatch
    from windflow_tpu.parallel import mesh as meshmod

    mesh = make_multihost_mesh(local_data=2)
    assert mesh.shape == {"data": 2, "key": 2 * nproc}, mesh.shape
    # host boundaries on the key axis: this process's devices own whole
    # key columns (the data-axis all_gather stays inside one host)
    for col in range(mesh.devices.shape[1]):
        owners = {d.process_index for d in mesh.devices[:, col]}
        assert len(owners) == 1, (col, owners)

    # -- keyed reduce: each process stages only the lanes IT ingested ------
    K, CAP = 16, 256
    rng = np.random.default_rng(5)
    keys = rng.integers(0, K, CAP)             # full input derivable by all
    vals = rng.integers(0, 1000, CAP).astype(np.float64)
    lo, hi = proc_id * CAP // nproc, (proc_id + 1) * CAP // nproc
    hb = HostBatch([{"k": int(k), "v": float(v)}
                    for k, v in zip(keys[lo:hi], vals[lo:hi])],
                   list(range(lo, hi)), 0)
    db = stage_local(hb, CAP, mesh)
    fn = meshmod.make_sharded_keyed_reduce(
        mesh, CAP, K, lambda a, b: {"k": a["k"], "v": a["v"] + b["v"]},
        key_fn=lambda t: t["k"], use_psum=False)
    table, has = fn(db.payload, db.valid)
    expected = np.zeros(K)
    for k, v in zip(keys, vals):
        expected[k] += v
    got = np.asarray(table["v"])      # replicated output: readable anywhere
    np.testing.assert_allclose(got, expected, rtol=1e-6)
    assert bool(np.asarray(has).all())
    print(f"proc {proc_id}: keyed reduce across {nproc} processes OK",
          flush=True)

    # -- key-sharded FFAT CB windows across the process boundary -----------
    Kf, CAPf, Pn, R, D = 8, 64, 4, 4, 1
    lift = lambda t: t["v"]
    comb = lambda a, b: a + b
    key_fn = lambda t: t["k"]
    step = meshmod.make_sharded_ffat_step(mesh, CAPf, Kf, Pn, R, D,
                                          lift, comb, key_fn)
    # float32 agg seed matching the f32 value lane: an x64-default f64
    # seed made one state leaf flip f64→f32 after the first step, so
    # both processes ran TWO compiled program versions whose collectives
    # could interleave across the Gloo pairs — an intermittent
    # preamble-size abort (112 vs 56 B = f64 vs f32) this pins away
    state = meshmod.make_sharded_ffat_state(
        jnp.zeros((), jnp.float32), Kf, R, mesh)

    from windflow_tpu.windows.ffat_kernels import (make_ffat_state,
                                                   make_ffat_step)
    ref_step = jax.jit(make_ffat_step(CAPf, Kf, Pn, R, D, lift, comb,
                                      key_fn))
    ref_state = make_ffat_state(jnp.zeros((), jnp.float32), Kf, R)

    from jax.sharding import NamedSharding, PartitionSpec
    bsh = NamedSharding(mesh, PartitionSpec(meshmod.DATA_AXIS))

    def global_put(a):
        # data-sharded global array; every process derives the full input
        # (same seed) and contributes each device's slice
        return jax.make_array_from_callback(
            a.shape, bsh, lambda idx: a[idx])

    rng2 = np.random.default_rng(7)
    got_w, exp_w = {}, {}
    for _ in range(6):
        k_np = rng2.integers(0, Kf, CAPf).astype(np.int32)
        v_np = rng2.integers(0, 100, CAPf).astype(np.float32)  # exact sums
        ts_np = np.arange(CAPf, dtype=np.int64)
        ok_np = np.ones(CAPf, bool)
        payload = {"k": global_put(k_np), "v": global_put(v_np)}
        state, out, fired, _ = step(state, payload, global_put(ts_np),
                                    global_put(ok_np))
        # reference single-chip run on local, unsharded arrays
        ref_payload = {"k": jnp.asarray(k_np), "v": jnp.asarray(v_np)}
        ref_state, rout, rfired, _ = ref_step(
            ref_state, ref_payload, jnp.asarray(ts_np), jnp.asarray(ok_np))
        fired_np = process_allgather(fired, tiled=True)
        out_np = {kk: process_allgather(v, tiled=True)
                  for kk, v in out.items()}
        for o, f, dst in ((out_np, fired_np, got_w),
                          ({kk: np.asarray(v) for kk, v in rout.items()},
                           np.asarray(rfired), exp_w)):
            for i in np.nonzero(f)[0]:
                dst[(int(o["key"][i]), int(o["wid"][i]))] = \
                    float(o["value"][i])
    assert len(exp_w) > 0
    assert got_w == exp_w, (len(got_w), len(exp_w))
    print(f"proc {proc_id}: FFAT windows across {nproc} processes OK",
          flush=True)

    # -- WHOLE PipeGraph.run() spanning the process boundary ---------------
    # (VERDICT r4 item 5: drive the framework layers, not just the mesh
    # primitives).  Every process builds the SAME graph over the multihost
    # mesh; its Source yields only the tuples THIS process ingests, the
    # staging emitter assembles global batches shard-locally, the
    # key-sharded FFAT runs as a collective program, and each process's
    # sink receives the windows of its OWN key shards.  Lockstep contract:
    # identical batch cadence per process (equal stream lengths, count
    # punctuation disabled) — the sharded steps are collective programs.
    import dataclasses

    import windflow_tpu as wf

    KG, OBS, NBATCH = 8, 128, 4
    local_cap = OBS // nproc
    n_local = NBATCH * local_cap

    def gen():
        # global record g = (key g%KG, value g); process p ingests the
        # odd/even interleave so both ingest streams are non-trivial
        for j in range(n_local):
            g = j * nproc + proc_id
            yield {"k": g % KG, "v": float(g), "ts": g * 1000}

    got = {}
    src = (wf.Source_Builder(gen)
           .withTimestampExtractor(lambda t: t["ts"])
           .withOutputBatchSize(OBS).build())
    win = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                      lambda a, b: a + b)
           .withKeyBy(lambda t: t["k"]).withMaxKeys(KG)
           .withCBWindows(16, 8).build())
    snk = wf.Sink_Builder(
        lambda r: got.__setitem__((r["key"], r["wid"]), r["value"])
        if r is not None else None).build()
    cfg = dataclasses.replace(wf.default_config, mesh=mesh,
                              punctuation_interval_usec=1 << 50)
    g = wf.PipeGraph("dcn_graph", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT, config=cfg)
    g.add_source(src).add(win).add_sink(snk)
    g.run()

    # oracle: the SAME graph run single-chip (no mesh) in-process over the
    # LOGICAL staged lane order, restricted to this process's key range.
    # Logical order under fully-sharded staging: lanes land at each
    # process's (data, key) blocks in block-index order (batch.py
    # _stage_soa), so logical block i of a batch holds a block-size run
    # of the rows of the process owning key column i % kk.  A whole-graph
    # oracle keeps EOS partial-window flush semantics identical by
    # construction.
    dd, kk = mesh.shape["data"], mesh.shape["key"]
    n_blk, bsz = dd * kk, OBS // (dd * kk)
    lk = kk // nproc
    blocks_of = {p: [i for i in range(n_blk) if (i % kk) // lk == p]
                 for p in range(nproc)}

    def gen_logical():
        for b in range(NBATCH):
            for blk in range(n_blk):
                p = (blk % kk) // lk
                bi = blocks_of[p].index(blk)
                for r_ in range(bsz):
                    j = b * local_cap + bi * bsz + r_
                    gidx = j * nproc + p
                    yield {"k": gidx % KG, "v": float(gidx),
                           "ts": gidx * 1000}

    ref_got = {}
    src_r = (wf.Source_Builder(gen_logical)
             .withTimestampExtractor(lambda t: t["ts"])
             .withOutputBatchSize(OBS).build())
    win_r = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                        lambda a, b: a + b)
             .withKeyBy(lambda t: t["k"]).withMaxKeys(KG)
             .withCBWindows(16, 8).build())
    snk_r = wf.Sink_Builder(
        lambda r: ref_got.__setitem__((r["key"], r["wid"]), r["value"])
        if r is not None else None).build()
    g_ref = wf.PipeGraph("dcn_graph_oracle", wf.ExecutionMode.DEFAULT,
                         wf.TimePolicy.EVENT)
    g_ref.add_source(src_r).add(win_r).add_sink(snk_r)
    g_ref.run()
    klo = proc_id * KG // nproc
    khi = (proc_id + 1) * KG // nproc
    exp_g = {kw: v for kw, v in ref_got.items() if klo <= kw[0] < khi}
    if got.keys() != exp_g.keys():
        print("DIFF only-got:", sorted(got.keys() - exp_g.keys())[:6],
              "only-exp:", sorted(exp_g.keys() - got.keys())[:6],
              flush=True)
    else:
        for kw in exp_g:
            if abs(got[kw] - exp_g[kw]) >= 1e-4:
                print("DIFF val", kw, got[kw], exp_g[kw], flush=True)
    assert got.keys() == exp_g.keys(), (proc_id, len(got), len(exp_g))
    for kw in exp_g:
        assert abs(got[kw] - exp_g[kw]) < 1e-4, kw
    print(f"proc {proc_id}: whole PipeGraph.run() across {nproc} "
          f"processes OK ({len(got)} windows on local key shards)",
          flush=True)

    # -- per-host wire/H2D attribution (wire round, sweep ledger) ----------
    # each host packs and stages only its LOCAL chips' shard, and the
    # ledger's wire subsection must say so: this process's staged bytes
    # are 1/nproc of the global lanes, not the global batch re-counted
    # per host.  Record lanes here: k/v/ts payload (int64+float64+int64)
    # + ts lane (int64) + valid (bool) = 33 B per global lane.
    wsec = (g.stats().get("Sweep") or {}).get("wire") or {}
    assert wsec.get("process_index") == proc_id, wsec
    assert wsec.get("process_count") == nproc, wsec
    expected_local = 33 * OBS * NBATCH // nproc
    assert wsec.get("wire_bytes") == expected_local, \
        (wsec, expected_local)
    # mesh staging is per-shard assembly, never the packed wire path:
    # wire and logical bytes agree on this leg
    assert wsec.get("logical_bytes") == wsec.get("wire_bytes"), wsec
    print(f"proc {proc_id}: per-host wire ledger OK "
          f"({wsec['wire_bytes']} B local of "
          f"{wsec['wire_bytes'] * nproc} B global)", flush=True)
    print(f"proc {proc_id}: DCN_WORKER_OK", flush=True)


if __name__ == "__main__":
    main()
