"""Backpressure: a fast source against a slow consumer must not grow inboxes
without bound (reference: in-transit GPU batch throttling,
``recycling_gpu.hpp:88-126``, and FF_BOUNDED_BUFFER bounded queues,
``README.md:36-39``)."""

import dataclasses

import jax.numpy as jnp

from windflow_tpu.basic import Config, RoutingMode
from windflow_tpu.graph.pipegraph import PipeGraph
from windflow_tpu.ops.map_op import Map
from windflow_tpu.ops.sink import Sink
from windflow_tpu.ops.source import Source
from windflow_tpu.ops.tpu import MapTPU


def _run_bounded(cfg, ops, n_items):
    g = PipeGraph("bp", config=cfg)
    src = Source(lambda: iter(range(n_items)))  # tick chunk 256, batches of 1
    mp = g.add_source(src)
    for op in ops:
        mp.add(op)
    got = []
    mp.add_sink(Sink(lambda x: got.append(x) if x is not None else None))
    g.run()
    return g, got


def test_host_inbox_bounded():
    cfg = dataclasses.replace(Config(), max_inbox_messages=32,
                              sweep_drain_limit=8)
    g, got = _run_bounded(cfg, [Map(lambda x: x + 1)], 5000)
    assert sorted(got) == list(range(1, 5001))
    # One source tick (256 emits) can overshoot the cap before the next
    # sweep's throttle check; the bound is cap + one tick.
    assert g._max_inbox_seen <= 32 + 256
    assert g._throttle_events > 0


def test_device_inflight_bounded():
    # source stages 4 device batches per tick (chunk 256 / capacity 64), the
    # consumer drains at most 1 per sweep: without throttling inflight device
    # batches would grow to n/64 = 64
    cfg = dataclasses.replace(Config(), max_inflight_batches=2,
                              sweep_drain_limit=1, source_tick_chunk=256)
    g = PipeGraph("bp_dev", config=cfg)
    n = 4096
    src = Source(lambda: iter(range(n)), output_batch_size=64)
    got = []
    g.add_source(src) \
        .add(MapTPU(lambda x: x * jnp.int32(2))) \
        .add_sink(Sink(lambda x: got.append(x) if x is not None else None))
    g.run()
    assert sorted(got) == [2 * i for i in range(n)]
    # cap + one tick's overshoot (4 staged batches)
    assert g._max_inflight_device_seen <= 2 + 4
    assert g._throttle_events > 0


def test_stats_report_backpressure_reality():
    cfg = dataclasses.replace(Config(), max_inbox_messages=16,
                              sweep_drain_limit=4)
    g, _ = _run_bounded(cfg, [Map(lambda x: x)], 2000)
    s = g.stats()
    assert "max_inbox_messages=16" in s["Backpressure"]
    assert s["Backpressure_throttle_events"] == g._throttle_events > 0
    assert s["Max_inbox_depth_seen"] == g._max_inbox_seen
