"""Multicast aliasing: an in-place operator on one branch of a split (or one
replica of a broadcast) must not corrupt the tuples its siblings see
(reference: ``Map`` copyOnWrite after multicast, ``map.hpp:57-215``)."""

import windflow_tpu as wf


def test_split_multicast_inplace_isolation():
    n = 200
    mutated, pristine = [], []

    def inplace_bump(t):
        t["v"] += 1000   # in-place variant: returns None
        return None

    g = wf.PipeGraph("cow_split")
    src = wf.Source_Builder(
        lambda: iter({"i": i, "v": i} for i in range(n))).build()
    mp = g.add_source(src).add(wf.Map(lambda t: dict(t), "prep"))
    mp.split(lambda t: (0, 1), 2)   # every tuple goes to BOTH branches
    mp.select(0).add(wf.Map(inplace_bump, "bump")) \
        .add_sink(wf.Sink_Builder(
            lambda t: mutated.append(t) if t is not None else None).build())
    mp.select(1).add_sink(wf.Sink_Builder(
        lambda t: pristine.append(t) if t is not None else None).build())
    g.run()

    assert sorted(t["v"] for t in mutated) == [i + 1000 for i in range(n)]
    # the sibling branch must see unmutated values
    assert sorted(t["v"] for t in pristine) == list(range(n))


def test_broadcast_inplace_isolation():
    n = 100
    got = []

    def make_bump(delta):
        def bump(t):
            t["v"] += delta
            return None
        return bump

    # BROADCAST into an in-place Map with parallelism 2: both replicas see
    # every tuple; each must mutate a private copy
    g = wf.PipeGraph("cow_bcast")
    src = wf.Source_Builder(
        lambda: iter({"i": i, "v": i} for i in range(n))) \
        .withOutputBatchSize(16).build()
    bump = wf.Map(make_bump(1000), "bump", parallelism=2,
                  routing=wf.RoutingMode.BROADCAST)
    g.add_source(src).add(bump).add_sink(
        wf.Sink_Builder(
            lambda t: got.append(t) if t is not None else None).build())
    g.run()

    # each replica emits all n tuples, each bumped exactly once from a
    # pristine copy: 2n outputs, every value i+1000 exactly twice
    assert len(got) == 2 * n
    assert sorted(t["v"] for t in got) == sorted(
        [i + 1000 for i in range(n)] * 2)
