"""Native host-runtime + bulk IO tests: the C++ library (keyby partition,
frame/CSV parsers, buffer pool, SPSC ring, watermark fold) against numpy
fallbacks, and the FrameSource bulk-ingest path end-to-end through the graph
(native parse → columnar staging → TPU ops → sink)."""

import ctypes
import struct

import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu import native
from windflow_tpu.io import FrameSource


def frames_bytes(records, nv=1):
    out = b""
    for k, ts, *vs in records:
        out += struct.pack("<qq" + "d" * nv, k, ts, *vs)
    return out


def test_native_builds_and_loads():
    assert native.is_available(), \
        "native library should build in this environment (g++ present)"


def test_hash_native_matches_numpy():
    L = native.lib()
    keys = np.array([0, 1, 2, -1, 123456789, 2 ** 62], np.int64)
    py = native.hash64(keys)
    for i, k in enumerate(keys):
        assert L.wf_hash64(int(k)) == int(py[i])


def test_keyby_partition_parity_and_counts():
    keys = np.random.default_rng(0).integers(-100, 100, 1000)
    for ndest in (1, 3, 8):
        dests, counts = native.keyby_partition(keys, ndest)
        exp = (native.hash64(keys.astype(np.int64)) %
               np.uint64(ndest)).astype(np.int32)
        np.testing.assert_array_equal(dests, exp)
        np.testing.assert_array_equal(
            counts, np.bincount(exp, minlength=ndest))


def test_parse_frames_roundtrip_and_carry():
    recs = [(i % 5, 1000 + i, float(i), float(-i)) for i in range(97)]
    buf = frames_bytes(recs, nv=2)
    # append a partial record: must be left unconsumed
    buf_partial = buf + b"\x01\x02\x03"
    keys, tss, vals, consumed = native.parse_frames(buf_partial, nv=2)
    assert consumed == len(buf)
    assert len(keys) == 97
    np.testing.assert_array_equal(keys, [r[0] for r in recs])
    np.testing.assert_array_equal(tss, [r[1] for r in recs])
    np.testing.assert_allclose(vals[:, 0], [r[2] for r in recs])
    np.testing.assert_allclose(vals[:, 1], [r[3] for r in recs])


def test_parse_csv_skips_malformed():
    buf = b"1,10,2.5\n2,20,3.5\nbogus line\n3,30,4.5\n4,40"  # last line partial
    keys, tss, vals, consumed = native.parse_csv(buf, nv=1)
    np.testing.assert_array_equal(keys, [1, 2, 3])
    np.testing.assert_array_equal(tss, [10, 20, 30])
    np.testing.assert_allclose(vals[:, 0], [2.5, 3.5, 4.5])
    assert buf[consumed:] == b"4,40"


def test_parse_csv_empty_field_does_not_steal_next_line():
    # "5,50,\n" has an empty value field: the whole line must be skipped
    # without consuming digits from the following line
    buf = b"5,50,\n6,60,7.5\n"
    keys, tss, vals, _ = native.parse_csv(buf, nv=1)
    np.testing.assert_array_equal(keys, [6])
    np.testing.assert_array_equal(tss, [60])
    np.testing.assert_allclose(vals[:, 0], [7.5])


def test_parse_csv_long_lines():
    # lines longer than any fixed scratch buffer must still parse (wide
    # records are normal for multi-field CSV)
    nv = 60
    fields = ",".join(f"{1.5:.10f}" for _ in range(nv))  # ~13 chars per field
    buf = (f"7,70,{fields}\n" * 3).encode()
    assert len(buf) > 3 * 512
    keys, tss, vals, consumed = native.parse_csv(buf, nv=nv)
    np.testing.assert_array_equal(keys, [7, 7, 7])
    np.testing.assert_array_equal(tss, [70, 70, 70])
    assert vals.shape == (3, nv)
    assert consumed == len(buf)


def test_parse_csv_empty_ts_skipped():
    # an empty ts field is malformed, not ts=0
    buf = b"1,,2.5\n2,20,3.5\n"
    keys, tss, vals, _ = native.parse_csv(buf, nv=1)
    np.testing.assert_array_equal(keys, [2])
    np.testing.assert_array_equal(tss, [20])


def test_frame_source_csv_without_trailing_newline():
    blob = b"1,10,2.5\n2,20,3.5"  # no trailing \n: last record still counts
    got = []
    src = FrameSource(lambda: iter([blob]), nv=1, fmt="csv",
                      output_batch_size=4)
    g = wf.PipeGraph("csv_tail", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    g.add_source(src).add_sink(wf.Sink_Builder(
        lambda t: got.append((t["key"], t["v0"])) if t else None).build())
    g.run()
    assert sorted(got) == [(1, 2.5), (2, 3.5)]


def test_min_watermark():
    WM = -1
    assert native.min_watermark(np.array([5, 3, 9], np.int64), WM) == 3
    assert native.min_watermark(np.array([5, WM, 9], np.int64), WM) == WM
    assert native.min_watermark(np.array([], np.int64), WM) == WM


@pytest.mark.parametrize("fmt", ["frames", "csv"])
def test_frame_source_to_tpu_pipeline(fmt):
    """bytes → FrameSource → MapTPU → keyed ReduceTPU → Sink vs oracle,
    with records split across chunk boundaries."""
    n, n_keys = 600, 7
    recs = [(i % n_keys, 1_000_000 + i, float(i)) for i in range(n)]
    if fmt == "frames":
        blob = frames_bytes(recs, nv=1)
    else:
        blob = b"".join(b"%d,%d,%f\n" % r for r in recs)

    def chunks():
        step = 997  # deliberately misaligned with the 24-byte record size
        for lo in range(0, len(blob), step):
            yield blob[lo:lo + step]

    sums = {}

    def sink_fn(t, ctx=None):
        if t is not None:
            sums[int(t["key"])] = sums.get(int(t["key"]), 0) + t["v0"]

    src = FrameSource(chunks, nv=1, fmt=fmt, output_batch_size=64)
    g = wf.PipeGraph("frames", wf.ExecutionMode.DEFAULT, wf.TimePolicy.EVENT)
    mp = g.add_source(src)
    mp.add(wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "v0": t["v0"] * 2.0}).build())
    mp.add(wf.ReduceTPU_Builder(
        lambda a, b: {"key": a["key"], "v0": a["v0"] + b["v0"]})
        .withKeyBy(lambda t: t["key"]).build())
    mp.add_sink(wf.Sink_Builder(sink_fn).build())
    g.run()

    exp = {}
    for k, _, v in recs:
        exp[k] = exp.get(k, 0) + 2.0 * v
    assert set(sums) == set(exp)
    for k in exp:
        assert abs(sums[k] - exp[k]) < 1e-6


def test_frame_source_to_host_sink_fallback_path():
    """Columns explode to per-tuple records for host destinations, and the
    pure-Python parser path (native disabled) agrees."""
    n = 100
    recs = [(i % 3, 10 + i, float(i)) for i in range(n)]
    blob = frames_bytes(recs, nv=1)

    def run(disable_native):
        import windflow_tpu.native as nat
        saved = nat._lib, nat._load_attempted
        if disable_native:
            nat._lib, nat._load_attempted = None, True
        try:
            total = [0.0]
            src = FrameSource(lambda: iter([blob]), nv=1,
                              output_batch_size=16)
            g = wf.PipeGraph("fs_host", wf.ExecutionMode.DEFAULT,
                             wf.TimePolicy.EVENT)
            g.add_source(src).add_sink(wf.Sink_Builder(
                lambda t: total.__setitem__(0, total[0] + t["v0"])
                if t else None).build())
            g.run()
            return total[0]
        finally:
            nat._lib, nat._load_attempted = saved

    exp = sum(r[2] for r in recs)
    assert run(False) == exp
    assert run(True) == exp


def test_columnar_sink_end_to_end():
    """bytes → FrameSource → MapTPU → columnar Sink: the sink receives
    SinkColumns (SoA numpy + timestamp lane), no per-record dicts, and the
    totals match the record-sink run exactly."""
    n, n_keys = 500, 5
    recs = [(i % n_keys, 1_000_000 + i, float(i)) for i in range(n)]
    blob = frames_bytes(recs, nv=1)

    def run(columnar):
        got = {"sum": 0.0, "rows": 0, "batches": 0, "ts_sum": 0}

        def col_sink(c, ctx=None):
            if c is None:
                return
            assert isinstance(c, wf.SinkColumns)
            assert isinstance(c.cols["v0"], np.ndarray)
            got["sum"] += float(c.cols["v0"].sum())
            got["rows"] += len(c)
            got["batches"] += 1
            got["ts_sum"] += int(c.tss.sum())

        def rec_sink(t, ctx=None):
            if t is None:
                return
            got["sum"] += t["v0"]
            got["rows"] += 1
            got["ts_sum"] += 0

        src = FrameSource(lambda: iter([blob]), nv=1, fmt="frames",
                          output_batch_size=64)
        b = wf.Sink_Builder(col_sink if columnar else rec_sink)
        if columnar:
            b = b.withColumnarSink()
        g = wf.PipeGraph("colsink", wf.ExecutionMode.DEFAULT,
                         wf.TimePolicy.EVENT)
        g.add_source(src).add(wf.MapTPU_Builder(
            lambda t: {"key": t["key"], "v0": t["v0"] * 2.0}).build()) \
            .add_sink(b.build())
        g.run()
        return got

    col = run(True)
    rec = run(False)
    assert col["rows"] == rec["rows"] == n
    assert abs(col["sum"] - rec["sum"]) < 1e-6
    assert col["batches"] <= -(-n // 64) + 1
    assert col["ts_sum"] == sum(r[1] for r in recs)


def test_chunk_spanning_batches_do_not_fire_ahead():
    """One parse chunk spanning many staged batches (chunk >> batch cap):
    head batches must NOT carry the chunk's watermark — it covers tail rows
    still buffered in the emitter — or TB windows fire ahead of unplaced
    data and drop it as late.  Ordered stream => exact results, zero late."""
    n, n_keys = 1000, 4
    TWIN, TSLIDE = 16_000, 4_000
    recs = [(i % n_keys, i * 1000, float(i)) for i in range(n)]
    blob = frames_bytes(recs, nv=1)   # ONE chunk, staged as 64-row batches

    got = {}
    src = FrameSource(lambda: iter([blob]), nv=1, fmt="frames",
                      output_batch_size=64)
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v0"], lambda a, b: a + b)
          .withTBWindows(TWIN, TSLIDE).withKeyBy(lambda t: t["key"])
          .withMaxKeys(n_keys).build())
    snk = wf.Sink_Builder(
        lambda r: got.__setitem__((int(r["key"]), int(r["wid"])),
                                  float(r["value"]))
        if r is not None else None).build()
    g = wf.PipeGraph("chunk_span", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    g.add_source(src).add(op).add_sink(snk)
    g.run()

    exp = {}
    per_key = {}
    for k, ts, v in recs:
        per_key.setdefault(k, []).append((ts, v))
    for k, pts in per_key.items():
        wids = set()
        for ts, _ in pts:
            last = ts // TSLIDE
            first = max(0, -(-(ts - TWIN + 1) // TSLIDE))
            wids.update(range(first, last + 1))
        for w in wids:
            vals = [v for ts, v in pts
                    if w * TSLIDE <= ts < w * TSLIDE + TWIN]
            if vals:
                exp[(k, w)] = sum(vals)
    st = op.dump_stats()
    assert st["Late_tuples_dropped"] == 0
    assert st["Pane_cells_evicted"] == 0
    assert got == exp


def test_keyby_placement_agrees_across_paths():
    """The per-tuple, columnar-native, and on-device keyby paths must place
    every key on the same replica (a keyed operator can be fed by host and
    device edges at once)."""
    import jax.numpy as jnp
    from windflow_tpu import native
    from windflow_tpu.parallel.emitters import (_splitmix64_dev,
                                                splitmix64_int)

    rnd = np.random.default_rng(3)
    keys = rnd.integers(-2**31, 2**31, 257).astype(np.int64)
    for n in (2, 3, 7):
        native_dest, _ = native.keyby_partition(keys, n)
        py_dest = np.array([splitmix64_int(int(k)) % n for k in keys])
        dev_dest = np.asarray(
            _splitmix64_dev(jnp.asarray(keys, jnp.int32)) % jnp.uint64(n))
        assert np.array_equal(native_dest, py_dest)
        assert np.array_equal(native_dest, dev_dest.astype(np.int64))
