"""Kafka integration tests (reference ``tests/kafka_tests/`` runs against a
live local broker; here the in-process broker plays that role, exercising
the same operator surface: per-replica consumers in one group, partition
assignment + rebalance, deserializer/serializer contracts, idle callbacks)."""

import pytest

import windflow_tpu as wf
from windflow_tpu.kafka import (InMemoryBroker, KafkaMessage, KafkaSink_Builder,
                                KafkaSinkMessage, KafkaSource_Builder)


def fill_topic(broker, topic, n, partitions=4):
    broker.create_topic(topic, partitions)
    prod = broker.producer()
    for i in range(n):
        prod.produce(topic, {"key": i % 8, "value": i},
                     key=str(i % 8).encode())
    prod.flush()
    return prod


# ---------------------------------------------------------------------------
# Broker semantics
# ---------------------------------------------------------------------------

def test_consumer_group_partitions_disjoint_and_complete():
    broker = InMemoryBroker()
    fill_topic(broker, "t", 100, partitions=6)
    c1, c2, c3 = (broker.consumer() for _ in range(3))
    for c in (c1, c2, c3):
        c.subscribe(["t"], "g1")
    parts = [set(c.assignment()) for c in (c1, c2, c3)]
    assert set.union(*parts) == {("t", p) for p in range(6)}
    assert sum(len(p) for p in parts) == 6  # disjoint
    got = []
    for c in (c1, c2, c3):
        got.extend(m.value["value"] for m in c.poll(1000))
    assert sorted(got) == list(range(100))


def test_rebalance_resumes_positions():
    """A partition handed to another member resumes at the group position —
    cooperative-rebalance semantics."""
    broker = InMemoryBroker()
    fill_topic(broker, "t", 60, partitions=2)
    c1 = broker.consumer()
    c1.subscribe(["t"], "g")
    first = c1.poll(30)          # reads some of both partitions
    assert len(first) == 30
    c2 = broker.consumer()
    c2.subscribe(["t"], "g")     # rebalance: one partition moves to c2
    assert len(c1.assignment()) == 1 and len(c2.assignment()) == 1
    rest = [m.value["value"] for c in (c1, c2) for m in c.poll(1000)]
    assert sorted([m.value["value"] for m in first] + rest) == list(range(60))
    c1.close()                   # leave: partitions return to c2
    assert len(c2.assignment()) == 2


def test_explicit_offsets():
    broker = InMemoryBroker()
    fill_topic(broker, "t", 20, partitions=1)
    c = broker.consumer()
    c.subscribe(["t"], "g_off", offsets=[15])
    vals = [m.value["value"] for m in c.poll(100)]
    assert vals == list(range(15, 20))


# ---------------------------------------------------------------------------
# Operators in graphs
# ---------------------------------------------------------------------------

def run_kafka_graph(par, n=200):
    broker = InMemoryBroker()
    fill_topic(broker, "in", n, partitions=4)
    broker.create_topic("out", 2)
    seen = {"eos_idle": 0}

    def deser(msg, shipper, ctx):
        # stop on first idle callback after the topic drains (reference:
        # deserializer returns false to end the stream)
        if msg is None:
            seen["eos_idle"] += 1
            return False
        assert isinstance(msg, KafkaMessage)
        shipper.pushWithTimestamp(msg.value, msg.timestamp_usec)
        return True

    def ser(item, ctx):
        if item["value"] % 2:
            return None  # drop odd values: serializer may skip
        return KafkaSinkMessage(topic="out", payload=item["value"],
                                key=str(item["key"]).encode())

    src = (KafkaSource_Builder(deser).withBrokers(broker)
           .withTopics("in").withGroupID("g").withIdleness(0)
           .withParallelism(par[0]).build())
    mp_op = (wf.Map_Builder(lambda t: {"key": t["key"],
                                       "value": t["value"] * 3})
             .withParallelism(par[1]).build())
    snk = (KafkaSink_Builder(ser).withBrokers(broker)
           .withParallelism(par[2]).build())
    g = wf.PipeGraph("kafka_graph", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(mp_op).add_sink(snk)
    g.run()
    # collect everything that landed in "out"
    c = broker.consumer()
    c.subscribe(["out"], "check")
    vals = [m.value for m in c.poll(10_000)]
    return sorted(vals), seen


@pytest.mark.parametrize("par", [(1, 1, 1), (3, 2, 2), (4, 1, 3)])
def test_kafka_source_to_sink(par):
    n = 200
    vals, seen = run_kafka_graph(par, n)
    expected = sorted(v * 3 for v in range(n) if (v * 3) % 2 == 0)
    assert vals == expected
    assert seen["eos_idle"] == par[0]  # one idle stop per source replica


def test_kafka_source_parallel_replicas_cover_all_partitions():
    broker = InMemoryBroker()
    fill_topic(broker, "in", 120, partitions=5)
    got = []

    def deser(msg, shipper):
        if msg is None:
            return False
        shipper.push(msg.value["value"])
        return True

    src = (KafkaSource_Builder(deser).withBrokers(broker)
           .withTopics("in").withGroupID("g2").withIdleness(0)
           .withParallelism(3).build())
    snk = (wf.Sink_Builder(lambda t, ctx=None: got.append(t)
                           if t is not None else None).build())
    g = wf.PipeGraph("kafka_par", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add_sink(snk)
    g.run()
    assert sorted(got) == list(range(120))


def test_kafka_context_exposes_clients():
    broker = InMemoryBroker()
    fill_topic(broker, "in", 10, partitions=1)
    seen = {}

    def deser(msg, shipper, ctx):
        seen["consumer"] = ctx.consumer is not None
        seen["assignment"] = ctx.consumer.assignment()
        if msg is None:
            return False
        shipper.push(msg.value)
        return True

    src = (KafkaSource_Builder(deser).withBrokers(broker)
           .withTopics("in").withIdleness(0).build())
    snk = wf.Sink_Builder(lambda t, ctx=None: None).build()
    g = wf.PipeGraph("kafka_ctx", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add_sink(snk)
    g.run()
    assert seen["consumer"] is True
    assert seen["assignment"] == [("in", 0)]


def test_real_broker_requires_client_library():
    from windflow_tpu.kafka.client import make_consumer
    with pytest.raises(wf.WindFlowError, match="confluent_kafka"):
        make_consumer("localhost:9092").subscribe(["t"], "g")


def test_confluent_adapter_paths_with_fake_module():
    """Exercise the real-client adapter code (ConfluentConsumer/Producer:
    subscribe with offset seeking, poll loop incl. error filtering and
    timestamp mapping, produce with BufferError backpressure retry) against
    a faked ``confluent_kafka`` module — the library isn't in this image
    and no broker runs in CI, but the adapter logic itself must not be dead
    code that only a production outage would first execute."""
    import sys
    import types

    log = {"produced": [], "assigned": [], "polled": 0}

    class FakeMsg:
        def __init__(self, topic, part, off, key, value, err=None, ts=(1, 5)):
            self._t, self._p, self._o = topic, part, off
            self._k, self._v, self._e, self._ts = key, value, err, ts

        def topic(self): return self._t
        def partition(self): return self._p
        def offset(self): return self._o
        def key(self): return self._k
        def value(self): return self._v
        def error(self): return self._e
        def timestamp(self): return self._ts

    class FakeTP:
        def __init__(self, topic, partition=0):
            self.topic, self.partition, self.offset = topic, partition, -1001

    class FakeConsumer:
        def __init__(self, conf):
            self.conf = conf
            self._queue = [
                FakeMsg("t", 0, 7, b"k", b"v0"),
                FakeMsg("t", 0, 8, None, b"bad", err="boom"),
                FakeMsg("t", 0, 9, None, b"v1", ts=(0, 0)),
            ]

        def subscribe(self, topics, on_assign=None):
            parts = [FakeTP(t) for t in topics]
            if on_assign:
                on_assign(self, parts)
            self._assigned = parts

        def incremental_assign(self, partitions):
            log["assigned"] = [(p.topic, p.partition, p.offset)
                               for p in partitions]

        def poll(self, timeout):
            log["polled"] += 1
            return self._queue.pop(0) if self._queue else None

        def assignment(self):
            return self._assigned

        def close(self):
            pass

    class FakeProducer:
        def __init__(self, conf):
            self._fail_once = True

        def produce(self, topic, value=None, key=None, **kw):
            if self._fail_once:
                self._fail_once = False
                raise BufferError("queue full")
            log["produced"].append((topic, value, key, kw))

        def poll(self, timeout):
            return 0

        def flush(self):
            log["flushed"] = True

    fake = types.ModuleType("confluent_kafka")
    fake.Consumer = FakeConsumer
    fake.Producer = FakeProducer
    fake.TopicPartition = FakeTP
    sys.modules["confluent_kafka"] = fake
    try:
        from windflow_tpu.kafka.client import make_consumer, make_producer
        c = make_consumer("broker:9092")
        c.subscribe(["t"], "grp", offsets=[7])
        assert log["assigned"] == [("t", 0, 7)]   # offset seeking ran
        msgs = c.poll(10)
        # the errored message is filtered; broker ts and ingest ts both map
        assert [m.value for m in msgs] == [b"v0", b"v1"]
        assert msgs[0].offset == 7 and msgs[0].timestamp_usec == 5000
        assert msgs[1].timestamp_usec > 0
        assert c.assignment() == [("t", 0)]
        c.close()

        p = make_producer("broker:9092")
        p.produce("t", b"x", key=b"kk", partition=3, timestamp_usec=9000)
        assert log["produced"] == [("t", b"x", b"kk",
                                    {"partition": 3, "timestamp": 9})]
        p.close()
        assert log.get("flushed")
    finally:
        del sys.modules["confluent_kafka"]


def test_per_partition_watermarks_one_replica_two_partitions():
    """A replica assigned several partitions must min-fold its watermark
    over the partitions' event-time progress (per-partition watermarks):
    poll rotation drains partitions in chunks, and a max-ts watermark
    would mark the lagging partition's tuples late.  TB windows with zero
    lateness downstream must still be exact with zero drops."""
    import jax.numpy as jnp

    import windflow_tpu as wf

    n = 400
    broker = InMemoryBroker()
    broker.create_topic("pp", 2)
    prod = broker.producer()
    for i in range(n):   # partition p gets key p, both spanning ts 0..n ms
        for p in (0, 1):
            prod.produce("pp", {"key": p, "v": i, "ts": i * 1000},
                         partition=p, timestamp_usec=i * 1000)
    prod.flush()

    got = {}
    src = (KafkaSource_Builder(
            lambda msg, shipper: shipper.pushWithTimestamp(
                msg.value, msg.timestamp_usec)
            if msg is not None else False)
           .withBrokers(broker).withTopics("pp").withGroupID("ppg")
           .withIdleness(1000).withOutputBatchSize(64).build())
    win = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"], lambda a, b: a + b)
           .withTBWindows(16_000, 4_000).withKeyBy(lambda t: t["key"])
           .withMaxKeys(2).build())
    snk = wf.Sink_Builder(
        lambda r: got.__setitem__((int(r["key"]), int(r["wid"])),
                                  int(r["value"]))
        if r is not None else None).build()
    g = wf.PipeGraph("pp_wm", wf.ExecutionMode.DEFAULT, wf.TimePolicy.EVENT)
    g.add_source(src).add(win).add_sink(snk)
    g.run()

    st = win.dump_stats()
    assert st["Late_tuples_dropped"] == 0
    from conftest import tb_window_sums
    pts = [(i * 1000, i) for i in range(n)]
    exp = tb_window_sums({0: pts, 1: pts}, 16_000, 4_000)
    assert got == exp


def test_kafka_closing_functions_see_live_clients():
    """The closing function runs with the Kafka client still usable
    (reference runs kafka_closing_func before teardown): the source closer
    can read its assignment, the sink closer can produce a final marker."""
    import windflow_tpu as wf

    broker = InMemoryBroker()
    fill_topic(broker, "in", 30, partitions=2)
    broker.create_topic("out", 1)
    src_assignment = []

    src = (KafkaSource_Builder(
            lambda msg, shipper: shipper.push(msg.value)
            if msg is not None else False)
           .withBrokers(broker).withTopics("in").withGroupID("cl")
           .withIdleness(1000)
           .withKafkaClosingFunction(
               lambda ctx: src_assignment.extend(ctx.consumer.assignment()))
           .withOutputBatchSize(8).build())
    snk = (KafkaSink_Builder(
            lambda t: KafkaSinkMessage("out", t))
           .withBrokers(broker)
           .withKafkaClosingFunction(
               lambda ctx: (ctx.producer.produce("out", {"final": True}),
                            ctx.producer.flush()))
           .build())
    g = wf.PipeGraph("kafka_closers", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add_sink(snk)
    g.run()

    assert src_assignment == [("in", 0), ("in", 1)]
    c = broker.consumer()
    c.subscribe(["out"], "check2")
    vals = [m.value for m in c.poll(1000)]
    assert {"final": True} in vals
    assert len(vals) == 31  # 30 records + the closer's marker


def test_heard_then_idle_partition_stops_gating():
    """A partition that delivered once and went silent must stop pinning
    the replica watermark after idle_time_usec — otherwise a live stream's
    windows stall forever behind one stale partition."""
    from windflow_tpu.basic import current_time_usecs
    from windflow_tpu.kafka.kafka_source import (KafkaSource,
                                                 KafkaSourceReplica)

    class StubConsumer:
        def assignment(self):
            return [("t", 0), ("t", 1)]

        def idle_partitions(self):
            return None   # unknown: exercises the wall-clock fallback

    class StubEmitter:
        def emit(self, item, ts, wm, shared=False, tid=None):
            pass

    op = KafkaSource(lambda m, s: None, object(), ["t"])
    rep = KafkaSourceReplica(op, 0)
    rep._consumer = StubConsumer()
    rep.emitter = StubEmitter()
    now = current_time_usecs()

    # p1 delivered once at ts=0 long ago; p0 is streaming now
    rep._part_max = {("t", 0): 500_000, ("t", 1): 0}
    rep._part_last_at = {("t", 0): now, ("t", 1): now - 1_000_000}
    assert rep._partition_wm() == 500_000  # p1 idle: no longer gating

    # p1 delivered recently: it gates again
    rep._part_last_at[("t", 1)] = now
    assert rep._partition_wm() == 0

    # and through the shipper: a push from p0 advances the wm past the
    # idle sibling
    rep._part_last_at[("t", 1)] = now - 1_000_000
    rep._cur_tp = ("t", 0)
    rep._shipper.pushWithTimestamp({"v": 1}, 600_000)
    assert rep.current_wm == 600_000


def test_steady_state_watermark_advances_when_caught_up():
    """The normal live steady state — consumer keeping pace, every poll
    drains its partition — must still advance the watermark (a drained
    partition that delivered THIS poll is live, not idle)."""
    from windflow_tpu.kafka.kafka_source import KafkaSource

    broker = InMemoryBroker()
    broker.create_topic("live", 1)
    prod = broker.producer()

    op = KafkaSource(
        lambda msg, shipper: shipper.pushWithTimestamp(
            msg.value, msg.timestamp_usec) if msg is not None else None,
        broker, ["live"])
    rep = op.build_replicas(wf.ExecutionMode.DEFAULT,
                            wf.TimePolicy.EVENT)[0]

    class NullEmitter:
        def emit(self, item, ts, wm, shared=False, tid=None):
            pass

    rep.emitter = NullEmitter()
    rep.start()
    for ts in (1_000, 2_000, 3_000):
        prod.produce("live", {"v": ts}, timestamp_usec=ts)
        rep.tick(10)                 # poll drains the partition each time
        assert rep.current_wm == ts  # watermark tracks the live partition


def test_assignment_policy_clause():
    """withAssignmentPolicy validates its argument and reaches the
    consumer; the in-memory broker serves all strategies with its
    cooperative assignment."""
    broker = InMemoryBroker()
    fill_topic(broker, "ap", 20, partitions=2)
    got = []
    src = (KafkaSource_Builder(
            lambda msg, shipper: shipper.push(msg.value)
            if msg is not None else False)
           .withBrokers(broker).withTopics("ap").withGroupID("apg")
           .withIdleness(1000).withAssignmentPolicy("roundrobin")
           .withOutputBatchSize(8).build())
    snk = wf.Sink_Builder(lambda t: got.append(t["value"])
                          if t is not None else None).build()
    g = wf.PipeGraph("ap", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add_sink(snk)
    g.run()
    assert sorted(got) == list(range(20))
    assert src.replicas[0]._consumer.assignment_policy == "roundrobin"

    with pytest.raises(wf.WindFlowError, match="assignment policy"):
        (KafkaSource_Builder(lambda m, s: None)
         .withBrokers(broker).withTopics("ap")
         .withAssignmentPolicy("mystery").build())


def test_revoked_partition_state_pruned_and_regain_fresh():
    """A partition revoked in a rebalance leaves no stale watermark
    tracking behind on the replica that lost it: _part_max/_part_seen_at/
    _part_last_at are pruned to the live assignment each poll, so a
    partition re-gained later starts a fresh grace window instead of
    inheriting a long-expired one (which would stop it gating the
    per-partition watermark fold and mark its backlog late)."""
    from windflow_tpu.kafka.kafka_source import KafkaSource

    broker = InMemoryBroker()
    fill_topic(broker, "t", 40, partitions=2)

    def deser(msg, shipper, ctx):
        if msg is None:
            return True
        shipper.pushWithTimestamp(msg.value["value"] + 1,
                                  msg.timestamp_usec)
        return True

    src = KafkaSource(deser, broker, ["t"], group_id="gprune",
                      idle_time_usec=10**12)

    class _StubEmitter:
        def emit(self, *a, **k):
            pass

        def propagate_punctuation(self, wm):
            pass

        def flush(self, wm):
            pass

    rep = src.replica_class(src, 0)
    rep.emitter = _StubEmitter()
    rep.start()
    rep.tick(100)                       # consumes both partitions
    assert set(rep._part_max) == {("t", 0), ("t", 1)}
    c2 = broker.consumer()
    c2.subscribe(["t"], "gprune")       # rebalance: one partition moves
    rep.tick(100)                       # next poll prunes revoked state
    live = set(rep._consumer.assignment())
    assert len(live) == 1
    assert set(rep._part_max) <= live
    assert set(rep._part_seen_at) <= live
    assert set(rep._part_last_at) <= live
    c2.close()
    rep._consumer.close()


def test_partitionless_replica_heartbeat_advances_watermark():
    """A replica whose assignment is EMPTY (parallelism > partition count)
    must still advance its watermark on idle-callback heartbeat pushes —
    no partition can lag it, so the per-partition gate does not apply."""
    from windflow_tpu.kafka.kafka_source import KafkaSource

    broker = InMemoryBroker()
    fill_topic(broker, "t", 10, partitions=1)

    def deser(msg, shipper, ctx):
        if msg is None:
            shipper.pushWithTimestamp({"hb": True}, 1_000_000_000)
            return False
        shipper.pushWithTimestamp(msg.value, msg.timestamp_usec)
        return True

    src = KafkaSource(deser, broker, ["t"], group_id="ghb",
                      idle_time_usec=0)

    class _StubEmitter:
        def emit(self, *a, **k):
            pass

        def propagate_punctuation(self, wm):
            pass

        def flush(self, wm):
            pass

    # claim the only partition with another member first, so the replica
    # under test joins with an empty assignment
    c_hold = broker.consumer()
    c_hold.subscribe(["t"], "ghb")
    rep = src.replica_class(src, 0)
    rep.emitter = _StubEmitter()
    rep.start()
    assert len(rep._consumer.assignment()) == 0
    rep.tick(100)        # no messages -> idle heartbeat push
    assert rep._exhausted
    assert rep.current_wm == 1_000_000_000
    c_hold.close()
