"""Static-analysis subsystem (windflow_tpu/analysis): the pre-flight graph
checker's diagnostic matrix, the hot-path AST lint, and the debug-mode race
detector.

The broken-graph matrix pins the exact ``WFxxx`` codes for compositions
that previously raised deep at runtime (or silently misbehaved): dtype
mismatch mid-chain, slide > length, keyby after sink, mesh-indivisible
parallelism, mixed watermark modes at merge — all caught by
``PipeGraph.check()`` with zero device work.
"""

import dataclasses
import importlib.util
import json
import os
import textwrap
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu import staging
from windflow_tpu.analysis import debug_concurrency as dbg
from windflow_tpu.analysis.diagnostics import (PreflightError,
                                               PreflightWarning)
from windflow_tpu.basic import Config
from windflow_tpu.monitoring.recorder import ReplicaRing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def codes(diags):
    return [d.code for d in diags]


def _rec_src(n=4, cap=8, fields=None):
    fields = fields or {"k": np.int32(0), "v": np.float32(0.0)}

    def gen():
        return iter({"k": i % 2, "v": float(i)} for i in range(n))

    return (wf.Source_Builder(gen).withOutputBatchSize(cap)
            .withRecordSpec(fields).build())


def _sink(acc=None):
    if acc is None:
        return wf.Sink_Builder(lambda r: None).build()
    return wf.Sink_Builder(
        lambda r: acc.append(r) if r is not None else None).build()


# ---------------------------------------------------------------------------
# broken-graph matrix: exact diagnostic codes, all violations reported
# ---------------------------------------------------------------------------

def test_dtype_mismatch_mid_chain_wf101():
    """A kernel that cannot consume the records reaching it (here: scalar
    field concatenated as if it were a vector) is caught abstractly, with
    the offending operator named."""
    g = wf.PipeGraph("bad_chain")
    bad = (wf.MapTPU_Builder(
        lambda t: {"v": jnp.concatenate([t["v"], t["v"]])})
        .withName("bad_map").build())
    g.add_source(_rec_src()).add(
        wf.MapTPU_Builder(lambda t: dict(t)).withName("ok_map").build()) \
     .add(bad).add_sink(_sink())
    diags = g.check()
    assert codes(diags) == ["WF101"]
    assert diags[0].node == "bad_map"
    assert diags[0].severity == "error"


def test_filter_predicate_not_bool_wf102():
    g = wf.PipeGraph("bad_pred")
    g.add_source(_rec_src()).add(
        wf.FilterTPU_Builder(lambda t: t["v"]).build()).add_sink(_sink())
    assert codes(g.check()) == ["WF102"]


def test_reduce_combiner_drops_field_wf103():
    g = wf.PipeGraph("bad_comb")
    g.add_source(_rec_src()).add(
        wf.ReduceTPU_Builder(lambda a, b: {"v": a["v"] + b["v"]})
        .build()).add_sink(_sink())
    ds = g.check()
    assert codes(ds) == ["WF103"]
    assert "structure" in ds[0].message


def test_key_extractor_not_integer_wf104():
    g = wf.PipeGraph("bad_key")
    g.add_source(_rec_src()).add(
        wf.ReduceTPU_Builder(lambda a, b: {"k": a["k"],
                                           "v": a["v"] + b["v"]})
        .withKeyBy(lambda t: t["v"]).build()).add_sink(_sink())
    assert "WF104" in codes(g.check())


def test_ffat_comb_structure_wf105():
    g = wf.PipeGraph("bad_ffat")
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                     lambda a, b: (a + b, a))
          .withCBWindows(4, 2).withKeyBy(lambda t: t["k"])
          .withMaxKeys(2).build())
    g.add_source(_rec_src()).add(op).add_sink(_sink())
    assert codes(g.check()) == ["WF105"]


def test_window_slide_exceeds_length_wf202():
    """Warning, not error: hopping windows with gaps are supported (the
    FFAT spec sweep pins their semantics), but a swapped (length, slide)
    silently drops gap tuples — surfaced loudly."""
    g = wf.PipeGraph("bad_win")
    op = (wf.Keyed_Windows_Builder(lambda vs: len(vs))
          .withCBWindows(4, 8).build())
    g.add_source(wf.Source_Builder(lambda: iter([])).build()) \
     .add(op).add_sink(_sink())
    ds = g.check()
    assert codes(ds) == ["WF202"]
    assert ds[0].severity == "warning"


def test_lateness_on_cb_window_wf203_warning():
    g = wf.PipeGraph("warn_win")
    op = (wf.Keyed_Windows_Builder(lambda vs: len(vs))
          .withCBWindows(8, 4).withLateness(1000).build())
    g.add_source(wf.Source_Builder(lambda: iter([])).build()) \
     .add(op).add_sink(_sink())
    ds = g.check()
    assert codes(ds) == ["WF203"]
    assert ds[0].severity == "warning"


def test_keyby_after_sink_wf301():
    """A keyed operator composed after the sink: today this either went
    dead (never receives data) or died at build; check() names both the
    post-sink operator (WF301) and the dangling tail (WF302)."""
    g = wf.PipeGraph("after_sink")
    mp = g.add_source(wf.Source_Builder(lambda: iter([])).build())
    mp.add(_sink())
    mp.add(wf.Keyed_Windows_Builder(lambda vs: 0).withCBWindows(2, 2)
           .withKeyBy(lambda t: t["k"]).build())
    got = codes(g.check())
    assert "WF301" in got and "WF302" in got


def test_missing_sink_wf302():
    g = wf.PipeGraph("no_sink")
    g.add_source(wf.Source_Builder(lambda: iter([])).build()) \
        .add(wf.Map_Builder(lambda t: t).build())
    assert codes(g.check()) == ["WF302"]


def test_mesh_indivisible_batch_wf401():
    from windflow_tpu.parallel.mesh import make_mesh
    cfg = dataclasses.replace(Config(), mesh=make_mesh(8, data=2))
    g = wf.PipeGraph("mesh_bad", config=cfg)
    g.add_source(_rec_src(cap=60)).add(
        wf.MapTPU_Builder(lambda t: dict(t)).build()).add_sink(_sink())
    ds = g.check()
    assert "WF401" in codes(ds)
    assert "not divisible" in ds[codes(ds).index("WF401")].message


def test_mesh_indivisible_keyspace_wf402():
    from windflow_tpu.parallel.mesh import make_mesh
    cfg = dataclasses.replace(Config(), mesh=make_mesh(8, data=2))
    g = wf.PipeGraph("mesh_keys", config=cfg)
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"], lambda a, b: a + b)
          .withCBWindows(4, 2).withKeyBy(lambda t: t["k"])
          .withMaxKeys(3).build())      # key axis extent is 4
    g.add_source(_rec_src(cap=64)).add(op).add_sink(_sink())
    assert "WF402" in codes(g.check())


def test_mixed_watermark_modes_at_merge_wf502():
    """EVENT-time merge of a timestamped branch with an extractor-less one:
    the merged watermark min-folds, so the dead branch gates every time
    window downstream — reported as the full set (WF501 on the source,
    WF502 at the merge, WF503 on the window)."""
    s1 = (wf.Source_Builder(lambda: iter([]))
          .withTimestampExtractor(lambda t: t["ts"]).build())
    s2 = wf.Source_Builder(lambda: iter([])).build()
    g = wf.PipeGraph("mix", wf.ExecutionMode.DEFAULT, wf.TimePolicy.EVENT)
    merged = g.add_source(s1).merge(g.add_source(s2))
    merged.add(wf.Keyed_Windows_Builder(lambda vs: 0)
               .withTBWindows(1000, 1000).build())
    merged.add_sink(_sink())
    got = codes(g.check())
    assert "WF502" in got
    assert "WF501" in got and "WF503" in got   # full set, not just first


def test_merged_branch_dtype_drift_reports_wf106():
    """Same field names, different dtypes across a merge: downstream
    kernels must not be silently validated against just the first
    branch's spec."""
    sa = (wf.Source_Builder(lambda: iter([])).withOutputBatchSize(8)
          .withRecordSpec({"v": np.int32(0)}).build())
    sb = (wf.Source_Builder(lambda: iter([])).withOutputBatchSize(8)
          .withRecordSpec({"v": np.float32(0)}).build())
    g = wf.PipeGraph("dtype_drift")
    merged = g.add_source(sa).merge(g.add_source(sb))
    merged.add(wf.MapTPU_Builder(lambda t: {"v": t["v"] & 7}).build())
    merged.add_sink(_sink())
    ds = g.check()
    assert codes(ds) == ["WF106"]
    assert "int32" in ds[0].message and "float32" in ds[0].message


def test_preflight_warn_mode_really_bypasses_capacity_backstop():
    """PreflightError's message promises preflight='warn' bypasses; the
    _build backstop must not re-raise what was just warned."""
    s1 = (wf.Source_Builder(lambda: iter({"k": 0, "v": float(i)}
                                         for i in range(8)))
          .withOutputBatchSize(7).build())
    s2 = (wf.Source_Builder(lambda: iter({"k": 1, "v": float(i)}
                                         for i in range(8)))
          .withOutputBatchSize(4).build())
    cfg = dataclasses.replace(Config(), preflight="warn")
    g = wf.PipeGraph("warn_cap", config=cfg)
    merged = g.add_source(s1).merge(g.add_source(s2))
    merged.add(wf.MapTPU_Builder(lambda t: dict(t)).build())
    merged.add(wf.ReduceTPU_Builder(
        lambda a, b: {"k": a["k"], "v": a["v"] + b["v"]})
        .withKeyBy(lambda t: t["k"]).withMaxKeys(2).build())
    merged.add_sink(_sink())
    with pytest.warns(PreflightWarning, match="WF403"):
        g.start()       # must not raise the build-time backstop
    g._finalize(dump=False)


def test_empty_merged_pipe_reports_wf304_instead_of_crashing():
    g = wf.PipeGraph("empty_merge")
    g.add_source(wf.Source_Builder(lambda: iter([])).build()) \
        .merge(g.add_source(wf.Source_Builder(lambda: iter([])).build()))
    assert "WF304" in codes(g.check())


def test_wf503_propagates_past_ops_after_a_merge():
    """Merge-connection edges sort last in _edges(); the watermark fold
    must still reach a TB window sitting BEHIND an intermediate operator
    downstream of the merge."""
    s1 = (wf.Source_Builder(lambda: iter([]))
          .withTimestampExtractor(lambda t: t["ts"]).build())
    s2 = wf.Source_Builder(lambda: iter([])).build()
    g = wf.PipeGraph("mix2", wf.ExecutionMode.DEFAULT, wf.TimePolicy.EVENT)
    merged = g.add_source(s1).merge(g.add_source(s2))
    merged.add(wf.Map_Builder(lambda t: t).build())
    merged.add(wf.Keyed_Windows_Builder(lambda vs: 0)
               .withTBWindows(1000, 1000).build())
    merged.add_sink(_sink())
    assert "WF503" in codes(g.check())


def test_check_never_invokes_host_map_user_functions():
    """Host user functions are arbitrary Python the runtime never traces;
    check() must not fire their side effects (device kernels are traced
    by jit at the first batch anyway, so eval_shape adds nothing new)."""
    calls = []

    def side_effectful(t):
        calls.append(t)
        return t

    g = wf.PipeGraph("host_pure")
    g.add_source(_rec_src()).add(
        wf.Map_Builder(side_effectful).build()).add_sink(_sink())
    assert g.check() == []
    assert calls == []


def test_debug_guard_is_exception_safe(debug_mode):
    """A kernel raising mid-dispatch must not leave a stale guard entry
    that false-positives a later access to the same stats record."""
    from windflow_tpu.ops.map_op import Map

    class Boom(RuntimeError):
        pass

    op = Map(lambda t: (_ for _ in ()).throw(Boom()), output_batch_size=0)
    rep = op.build_replicas(wf.ExecutionMode.DEFAULT,
                            wf.TimePolicy.INGRESS)[0]
    from windflow_tpu.batch import HostBatch
    with pytest.raises(Boom):
        rep._dispatch(HostBatch([{"v": 1}], [0], 0))
    # guard cleaned up: the next sample bracket works from ANY thread
    errs = []

    def other_thread():
        try:
            rep.stats.start_sample()
            rep.stats.end_sample()
        except wf.ConcurrencyViolation as e:
            errs.append(e)

    t = threading.Thread(target=other_thread)
    t.start()
    t.join()
    assert errs == []


def test_clean_graph_zero_diagnostics_and_no_device_transfers(monkeypatch):
    """A well-formed declared chain produces zero diagnostics, and check()
    is provably transfer-free: device_put is poisoned for its duration and
    the graph's H2D ledger stays zero afterwards."""
    g = wf.PipeGraph("clean")
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"], lambda a, b: a + b)
          .withCBWindows(4, 2).withKeyBy(lambda t: t["k"])
          .withMaxKeys(2).build())
    g.add_source(_rec_src()).add(
        wf.MapTPU_Builder(lambda t: {"k": t["k"], "v": t["v"] * 2.0})
        .build()).add(op).add_sink(_sink())

    def no_transfers(*a, **kw):
        raise AssertionError("check() must not transfer to device")

    monkeypatch.setattr(jax, "device_put", no_transfers)
    diags = g.check()
    monkeypatch.undo()
    assert diags == []
    assert g.stats()["Bytes_H2D_total"] == 0


# ---------------------------------------------------------------------------
# start() integration: Config.preflight modes
# ---------------------------------------------------------------------------

def _two_fault_graph():
    g = wf.PipeGraph("two_faults")
    g.add_source(_rec_src()).add(
        wf.MapTPU_Builder(
            lambda t: {"v": jnp.concatenate([t["v"], t["v"]])})
        .build()).add_sink(_sink())
    g.add_source(_rec_src()).add(
        wf.FilterTPU_Builder(lambda t: t["v"]).build()).add_sink(_sink())
    return g


def test_start_reports_all_violations_not_just_first():
    g = _two_fault_graph()
    with pytest.raises(PreflightError) as ei:
        g.start()
    err = ei.value
    assert sorted(d.code for d in err.diagnostics) == ["WF101", "WF102"]
    assert "WF101" in str(err) and "WF102" in str(err)
    assert isinstance(err, wf.WindFlowError)


def test_preflight_warn_mode_warns_and_runs():
    acc = []
    cfg = dataclasses.replace(Config(), preflight="warn")
    g = wf.PipeGraph("warn_run", config=cfg)
    op = (wf.Keyed_Windows_Builder(lambda vs: sum(v["v"] for v in vs))
          .withCBWindows(2, 1).withLateness(5).build())
    src = (wf.Source_Builder(
        lambda: iter({"k": 0, "v": i} for i in range(6)))
        .withOutputBatchSize(2).build())
    g.add_source(src).add(op).add_sink(_sink(acc))
    with pytest.warns(PreflightWarning, match="WF203"):
        g.run()
    assert acc     # the stream actually ran


def test_preflight_off_reaches_the_old_runtime_error():
    """The matrix cases used to raise mid-run; preflight='off' restores
    that behavior (proving check() now fronts a real runtime fault)."""
    cfg = dataclasses.replace(Config(), preflight="off")
    g = wf.PipeGraph("off_mode", config=cfg)
    g.add_source(_rec_src()).add(
        wf.MapTPU_Builder(
            lambda t: {"v": jnp.concatenate([t["v"], t["v"]])})
        .build()).add_sink(_sink())
    with pytest.raises(Exception) as ei:
        g.run()
    assert not isinstance(ei.value, PreflightError)


# ---------------------------------------------------------------------------
# tools/wf_lint.py
# ---------------------------------------------------------------------------

def test_wf_lint_runs_clean_on_the_repo():
    lint = _load_tool("wf_lint")
    findings = lint.lint_paths([os.path.join(REPO, "windflow_tpu")])
    assert findings == [], findings


def test_wf_lint_seeded_violation_fixture(tmp_path):
    fixture = tmp_path / "seeded.py"
    fixture.write_text(textwrap.dedent("""\
        import threading
        import numpy as np
        from windflow_tpu.analysis.hotpath import hot_path

        class Thing:
            __lock_guards__ = {"_lock": ("_state",)}

            def __init__(self):
                self._lock = threading.Lock()
                self._state = {}

            def bad_touch(self):
                self._state["x"] = 1

            def ok_touch(self):
                with self._lock:
                    self._state["x"] = 1

            @hot_path
            def hot(self, xs):
                buf = np.zeros(4)
                ys = [x for x in xs]
                np.asarray(xs)
                with self._lock:
                    pass
                return buf, ys

        def swallow():
            try:
                pass
            except Exception:
                pass
            try:
                pass
            except:
                pass
    """))
    lint = _load_tool("wf_lint")
    got = sorted(f["code"] for f in lint.lint_paths([str(fixture)]))
    assert got == ["WF701", "WF701", "WF702", "WF703", "WF711",
                   "WF712", "WF721"]
    assert lint.main([str(fixture)]) == 1    # CI gate contract


def test_wf_lint_allowlist_comment_suppresses_wf712(tmp_path):
    fixture = tmp_path / "allowed.py"
    fixture.write_text(textwrap.dedent("""\
        def probe(fn):
            try:
                return fn()
            except Exception:   # lint: broad-except-ok (speculative user
                # callback probe; any failure selects the fallback)
                return None
    """))
    lint = _load_tool("wf_lint")
    assert lint.lint_paths([str(fixture)]) == []


# ---------------------------------------------------------------------------
# tools/wf_check.py CLI
# ---------------------------------------------------------------------------

def test_wf_check_cli_json_on_broken_app(tmp_path, monkeypatch, capsys):
    app = tmp_path / "wfcheck_demo_app.py"
    app.write_text(textwrap.dedent("""\
        import numpy as np
        import jax.numpy as jnp
        import windflow_tpu as wf

        def make_graph():
            src = (wf.Source_Builder(lambda: iter([]))
                   .withOutputBatchSize(8)
                   .withRecordSpec({"v": np.float32(0)}).build())
            g = wf.PipeGraph("demo_broken")
            g.add_source(src).add(
                wf.MapTPU_Builder(
                    lambda t: {"v": jnp.concatenate([t["v"], t["v"]])})
                .build()).add_sink(
                wf.Sink_Builder(lambda r: None).build())
            return g
    """))
    monkeypatch.syspath_prepend(str(tmp_path))
    wf_check = _load_tool("wf_check")
    rc = wf_check.main(["wfcheck_demo_app", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["errors"] == 1
    assert out["diagnostics"][0]["code"] == "WF101"
    assert out["check_ms"] is not None


# ---------------------------------------------------------------------------
# debug-mode race detector (WF_TPU_DEBUG_CONCURRENCY)
# ---------------------------------------------------------------------------

@pytest.fixture
def debug_mode():
    dbg.set_enabled(True)
    try:
        yield
    finally:
        dbg.set_enabled(False)


def test_cross_thread_staging_pool_mutation_is_caught(debug_mode):
    """The acceptance case: a second thread mutating StagingPool
    bookkeeping without the lock gets an immediate diagnostic instead of
    silently corrupting the slot dict."""
    pool = staging.StagingPool(depth=2)
    pool.release(np.empty(64, np.uint32))      # locked path: fine
    caught = []

    def attack():
        try:
            pool._slots[999] = "raced"         # unlocked cross-thread write
        except wf.ConcurrencyViolation as e:
            caught.append(e)

    t = threading.Thread(target=attack, name="attacker")
    t.start()
    t.join()
    assert len(caught) == 1
    assert "StagingPool._slots" in str(caught[0])
    assert 999 not in pool._slots              # the write did not land
    # the public, locked API still works from any thread
    buf = pool.acquire(64)
    assert buf.shape == (64,)


def test_cross_thread_slot_deque_mutation_is_caught(debug_mode):
    """Dict reads hand out the mutable slot deque — unlocked mutation of
    the deque itself is the same race one level down."""
    pool = staging.StagingPool(depth=4)
    pool.release(np.empty(64, np.uint32))
    caught = []

    def attack():
        try:
            pool._slots[64].append((np.empty(64, np.uint32), None))
        except wf.ConcurrencyViolation as e:
            caught.append(e)

    t = threading.Thread(target=attack, name="deque-attacker")
    t.start()
    t.join()
    assert len(caught) == 1
    assert "slot deque" in str(caught[0])


def test_flag_off_pool_mutation_not_caught():
    assert not dbg.ENABLED
    pool = staging.StagingPool(depth=2)
    pool._slots[999] = "unchecked"     # plain dict when the flag is off
    assert pool._slots[999] == "unchecked"


def test_entry_guard_catches_overlapping_ring_writes(debug_mode):
    ring = ReplicaRing("op", 0, 64)
    dbg.enter(ring, "ReplicaRing.record")      # main thread mid-write
    caught = []

    def attack():
        try:
            ring.record(1, 0, 123)
        except wf.ConcurrencyViolation as e:
            caught.append(e)

    t = threading.Thread(target=attack, name="second-writer")
    t.start()
    t.join()
    dbg.exit_(ring)
    assert len(caught) == 1
    assert "single-consumer" in str(caught[0])
    ring.record(1, 0, 123)                     # sequential use stays fine
    assert ring.n == 1


def test_builder_cross_thread_append_is_caught(debug_mode):
    b = staging.PackedBatchBuilder([np.float32], 8)
    dbg.enter(b, "PackedBatchBuilder.append")  # main thread mid-append
    caught = []

    def attack():
        try:
            b.append([np.ones(2, np.float32)], np.arange(2, dtype=np.int64))
        except wf.ConcurrencyViolation as e:
            caught.append(e)

    t = threading.Thread(target=attack)
    t.start()
    t.join()
    dbg.exit_(b)
    assert len(caught) == 1
    b.abandon()


def test_pipeline_runs_clean_under_debug_flag(debug_mode):
    """No false positives: a real pipeline (staging + TPU op + worker
    pool) under WF_TPU_DEBUG_CONCURRENCY=1 completes normally."""
    old_pool = staging.default_pool()
    staging.set_default_pool(staging.StagingPool())    # debug-built pool
    try:
        acc = []
        cfg = dataclasses.replace(Config(), host_worker_threads=2)
        g = wf.PipeGraph("dbg_run", config=cfg)
        src = (wf.Source_Builder(
            lambda: iter({"k": i % 2, "v": float(i)} for i in range(64)))
            .withOutputBatchSize(16).build())
        g.add_source(src).add(
            wf.MapTPU_Builder(lambda t: {"k": t["k"], "v": t["v"] + 1.0})
            .build()).add_sink(_sink(acc))
        g.run()
        assert len(acc) == 64
    finally:
        staging.set_default_pool(old_pool)


def test_debug_flag_off_overhead_is_one_flag_check():
    """Asserted alongside the recorder's <2% budget
    (test_observability.py::test_recorder_overhead_within_budget): with
    the flag off the instrumented ring write stays in the tens of
    nanoseconds-per-call regime — the bound below is ~1000x slack and
    exists to catch the off-path accidentally doing real work."""
    assert not dbg.ENABLED
    ring = ReplicaRing("op", 0, 1024)
    n = 50_000
    t0 = time.perf_counter()
    for i in range(n):
        ring.record(i, 0, i)
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 20e-6, f"{per_call * 1e6:.2f} usec/call"


def test_diagnostic_code_table_is_consistent():
    from windflow_tpu.analysis import CODES
    for code, (sev, _desc) in CODES.items():
        assert code.startswith("WF") and code[2:].isdigit()
        assert sev in ("error", "warning")
    d = wf.Diagnostic("WF101", "boom", node="x")
    assert d.severity == "error"
    assert d.to_json()["code"] == "WF101"
    assert "WF101" in str(d)
