"""Punctuation cadence: watermarks must keep advancing on a live-but-idle
stream so time windows fire without new data (reference: emitters multicast
punctuations every WF_DEFAULT_WM_INTERVAL_USEC / WM_AMOUNT inputs,
``basic.hpp:189-206``, ``forward_emitter.hpp:226-262``)."""

import dataclasses
import time

import windflow_tpu as wf
from windflow_tpu.basic import Config


def test_tb_window_fires_while_source_idle():
    cfg = dataclasses.replace(Config(), punctuation_interval_usec=5_000)
    results = []
    state = {"fired_during_idle": False}

    def gen():
        for i in range(10):
            yield {"key": 0, "value": 1}
        # idle for ~150 ms — several window lengths — yielding None so the
        # scheduler keeps sweeping while no data arrives
        t_end = time.time() + 0.15
        while time.time() < t_end:
            time.sleep(0.005)
            yield None
        # the window holding the first 10 tuples must have fired by now,
        # strictly before EOS flushing could be responsible
        state["fired_during_idle"] = len(results) > 0
        for i in range(5):
            yield {"key": 0, "value": 1}

    win_op = (wf.Keyed_Windows_Builder(
                lambda items: sum(t["value"] for t in items))
              .withTBWindows(20_000, 20_000)   # 20 ms tumbling
              .withKeyBy(lambda t: t["key"])
              .build())
    src = wf.Source_Builder(gen).build()
    snk = wf.Sink_Builder(
        lambda r: results.append(r) if r is not None else None).build()

    g = wf.PipeGraph("idle_fire", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.INGRESS, config=cfg)
    g.add_source(src).add(win_op).add_sink(snk)
    g.run()

    assert state["fired_during_idle"], \
        "TB window did not fire during the idle period"
    assert sum(r.value for r in results) == 15


def test_punctuation_amount_triggers_flush():
    # with punctuation_amount=8 and a huge batch size, batches are flushed by
    # the count-cadence punctuation rather than sitting open until EOS
    cfg = dataclasses.replace(Config(), punctuation_amount=8,
                              punctuation_interval_usec=10**9)
    seen = []

    def gen():
        for i in range(32):
            yield i
        # idle long enough for several sweeps; count cadence already flushed
        for _ in range(3):
            yield None

    src = wf.Source_Builder(gen).withOutputBatchSize(10_000).build()
    snk = wf.Sink_Builder(
        lambda x: seen.append(x) if x is not None else None).build()
    g = wf.PipeGraph("amount", config=cfg)
    g.add_source(src).add(wf.Map(lambda x: x)).add_sink(snk)

    g.start()
    # run a few sweeps without letting the stream end: data must already be
    # moving because the count punctuation flushed the open batch
    for _ in range(6):
        g.step()
    assert len(seen) >= 8, "count-cadence punctuation did not flush batches"
    while not g.is_done():
        g.step()
    g._finalize()
    assert sorted(seen) == list(range(32))
