"""Window-SPEC sweep for the flagship FfatWindowsTPU: random (win, slide)
pairs — sliding, tumbling (win == slide), hopping with gaps (slide > win),
and coprime pairs where the pane decomposition degenerates to P = gcd = 1 —
each checked against a pure-Python oracle over random batch sizes.

The reference's window tests fix one spec per binary
(``tests/win_tests_gpu/test_win_fat_gpu_tb.cpp``); its randomized sweeps
vary parallelism/batching but never the spec.  The pane decomposition
(P = gcd(win, slide), R = win/P, D = slide/P) makes the spec itself the
riskiest input here, so this sweep varies it.
"""

import math
import random

import pytest

import windflow_tpu as wf

N_KEYS = 3
LENGTH = 300


def stream():
    return [{"key": i % N_KEYS, "value": i, "ts": i * 1000}
            for i in range(LENGTH)]


def oracle_cb(win, slide):
    """Per-key count windows incl. EOS partials: window w covers that key's
    arrivals [w*slide, w*slide+win) and exists iff its start is before the
    key's end-of-stream."""
    per_key = {}
    for t in stream():
        per_key.setdefault(t["key"], []).append(t["value"])
    exp = {}
    for k, vals in per_key.items():
        w = 0
        while w * slide < len(vals):
            seg = vals[w * slide: w * slide + win]
            if seg:
                exp[(k, w)] = sum(seg)
            w += 1
    return exp


def oracle_tb(win_us, slide_us):
    """Per-key time windows: every window containing >= 1 tuple fires with
    its full contents (empty windows never fire)."""
    from conftest import tb_window_sums
    per_key = {}
    for t in stream():
        per_key.setdefault(t["key"], []).append((t["ts"], t["value"]))
    return tb_window_sums(per_key, win_us, slide_us)


def run_ffat_tpu(win_type, win, slide, batch, comb=None, monoid=None):
    got = {}
    src = (wf.Source_Builder(lambda: iter(stream()))
           .withTimestampExtractor(lambda t: t["ts"])
           .withOutputBatchSize(batch).build())
    b = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                    comb or (lambda a, b: a + b))
         .withKeyBy(lambda t: t["key"]).withMaxKeys(N_KEYS))
    if monoid is not None:
        b = b.withMonoidCombiner(monoid)
    if win_type == "cb":
        b = b.withCBWindows(win, slide)
    else:
        b = b.withTBWindows(win * 1000, slide * 1000)
    snk = wf.Sink_Builder(
        lambda r: got.__setitem__((r["key"], r["wid"]), r["value"])
        if r is not None else None).build()
    g = wf.PipeGraph("spec_sweep", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    g.add_source(src).add(b.build()).add_sink(snk)
    g.run()
    return got


# spec classes: sliding, tumbling, hopping-with-gap, coprime (P = 1), and a
# slide-1 stress (D = 1, maximal window overlap)
SPECS = [
    # tier-1 keeps ONE spec per sweep family: (9,5), the coprime P=1
    # decomposition — the degenerate pane arithmetic every other class
    # contains (R and D both > 1, no pane sharing).  The sliding,
    # tumbling, gap, slide-1, and second-coprime classes ride the
    # nightly leg (calibration-round headroom pass) — each is the same
    # oracle on a different (win, slide) pair, 3-6s per cell x 3 sweeps
    pytest.param(16, 4, marks=pytest.mark.slow),   # sliding, P=4 R=4 D=1
    pytest.param(12, 12, marks=pytest.mark.slow),  # tumbling, R=1 D=1
    pytest.param(6, 10, marks=pytest.mark.slow),   # hopping, 4-count gap
    pytest.param(7, 3, marks=pytest.mark.slow),    # coprime: P=1 R=7 D=3
    (9, 5),      # coprime: P=1 R=9 D=5
    pytest.param(10, 1, marks=pytest.mark.slow),   # slide-1: R=10 D=1
]


@pytest.mark.parametrize("win,slide", SPECS)
def test_cb_spec(win, slide):
    exp = oracle_cb(win, slide)
    rnd = random.Random(win * 100 + slide)
    for _ in range(2):
        batch = rnd.randint(1, 96)
        got = run_ffat_tpu("cb", win, slide, batch)
        assert got == exp, (win, slide, batch,
                            len(got), len(exp))


@pytest.mark.parametrize("win,slide", SPECS)
def test_tb_spec(win, slide):
    exp = oracle_tb(win * 1000, slide * 1000)
    rnd = random.Random(win * 100 + slide + 1)
    for _ in range(2):
        batch = rnd.randint(1, 96)
        got = run_ffat_tpu("tb", win, slide, batch)
        assert got == exp, (win, slide, batch,
                            len(got), len(exp))


@pytest.mark.parametrize("win_type", ["cb", "tb"])
@pytest.mark.parametrize("win,slide", SPECS)
def test_monoid_max_spec(win_type, win, slide):
    """Declared-max across the whole spec space (sliding / tumbling /
    gap-hopping / coprime / slide-1): the scatter-combine and sort-free
    placements must equal the undeclared flag-aware machinery EXACTLY on
    every pane decomposition (max is idempotent, so bit-identical).
    ``value`` lanes here are the stream's non-negative ints — the
    strictly-negative identity hunt lives in test_monoid_combiner; this
    sweep targets the spec-dependent pane/firing arithmetic instead."""
    import jax.numpy as jnp
    comb = lambda a, b: jnp.maximum(a, b)
    rnd = random.Random(win * 10 + slide)
    batch = rnd.randint(1, 96)
    got = run_ffat_tpu(win_type, win, slide, batch, comb=comb,
                       monoid="max")
    want = run_ffat_tpu(win_type, win, slide, batch, comb=comb)
    assert got == want and len(got) > 0, (win_type, win, slide, batch)


def _host_builder(family, nonin):
    if family == "keyed":
        return wf.Keyed_Windows_Builder(nonin)
    if family == "paned":
        return wf.Paned_Windows_Builder(nonin, lambda panes: sum(panes))
    if family == "mapreduce":
        return wf.MapReduce_Windows_Builder(nonin,
                                            lambda partials: sum(partials))
    if family == "ffat_host":
        return wf.Ffat_Windows_Builder(lambda t: t["value"],
                                       lambda a, b: a + b)
    raise AssertionError(family)


@pytest.mark.parametrize("family", ["keyed", "paned", "mapreduce",
                                    "ffat_host"])
@pytest.mark.parametrize("win,slide", [(16, 4), (12, 12), (6, 10), (7, 3)])
def test_host_families_tb_spec(family, win, slide):
    """Host window families across the same spec classes, TB form (the
    reference's per-op single-spec binaries, widened to the spec space)."""
    exp = oracle_tb(win * 1000, slide * 1000)
    nonin = lambda items: sum(t["value"] for t in items)
    got = {}
    src = (wf.Source_Builder(lambda: iter(stream()))
           .withTimestampExtractor(lambda t: t["ts"])
           .withOutputBatchSize(13).build())
    op = (_host_builder(family, nonin)
          .withTBWindows(win * 1000, slide * 1000)
          .withKeyBy(lambda t: t["key"]).build())
    snk = wf.Sink_Builder(
        lambda r: got.__setitem__((r.key, r.wid), int(r.value))
        if r is not None else None).build()
    g = wf.PipeGraph("host_spec", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    g.add_source(src).add(op).add_sink(snk)
    g.run()
    assert got == exp, (family, win, slide, len(got), len(exp))


@pytest.mark.parametrize("family", ["keyed", "paned", "mapreduce",
                                    "ffat_host"])
@pytest.mark.parametrize("win,slide", [(16, 4), (12, 12), (6, 10), (7, 3)])
def test_host_families_cb_spec(family, win, slide):
    exp = oracle_cb(win, slide)
    nonin = lambda items: sum(t["value"] for t in items)
    got = {}
    src = (wf.Source_Builder(lambda: iter(stream()))
           .withTimestampExtractor(lambda t: t["ts"])
           .withOutputBatchSize(13).build())
    op = (_host_builder(family, nonin)
          .withCBWindows(win, slide)
          .withKeyBy(lambda t: t["key"]).build())
    snk = wf.Sink_Builder(
        lambda r: got.__setitem__((r.key, r.wid), int(r.value))
        if r is not None else None).build()
    g = wf.PipeGraph("host_spec_cb", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    g.add_source(src).add(op).add_sink(snk)
    g.run()
    assert got == exp, (family, win, slide, len(got), len(exp))
