"""Window operator tests in the reference's style (``tests/win_tests/``):
every window operator × {count-based, time-based}, swept over random
parallelism/batch sizes with a pure-Python oracle
(cf. ``test_win_{kw,pw,paw,mrw,fat}_{cb,tb}.cpp``)."""

import random

import pytest

import windflow_tpu as wf


N_KEYS = 4
LENGTH = 400


def stream():
    # ordered event-time stream: ts = i milliseconds
    return [{"key": i % N_KEYS, "value": i, "ts": i * 1000}
            for i in range(LENGTH)]


def oracle_cb(win, slide):
    """Expected (#windows, total sum) for per-key count windows, including
    EOS partials (windows whose start index is before the key's end)."""
    per_key = {}
    for t in stream():
        per_key.setdefault(t["key"], []).append(t["value"])
    count, total = 0, 0
    for vals in per_key.values():
        w = 0
        while w * slide < len(vals):
            items = vals[w * slide: w * slide + win]
            count += 1
            total += sum(items)
            w += 1
    return count, total


def oracle_tb(win_us, slide_us):
    """Expected (#windows, total) for per-key time windows: every window that
    contains at least one tuple fires, with its full contents."""
    per_key = {}
    for t in stream():
        per_key.setdefault(t["key"], []).append((t["ts"], t["value"]))
    count, total = 0, 0
    for pts in per_key.values():
        wids = set()
        for ts, _ in pts:
            last = ts // slide_us
            first = max(0, -(-(ts - win_us + 1) // slide_us))
            wids.update(range(first, last + 1))
        for w in sorted(wids):
            items = [v for ts, v in pts
                     if w * slide_us <= ts < w * slide_us + win_us]
            if items:
                count += 1
                total += sum(items)
    return count, total


class WinAcc:
    def __init__(self):
        self.count = 0
        self.total = 0

    def __call__(self, r):
        if r is not None:
            self.count += 1
            self.total += int(r.value)


def run_graph(win_op, batch, mode=wf.ExecutionMode.DEFAULT,
              sink_parallelism=1):
    acc = WinAcc()
    src = (wf.Source_Builder(lambda: iter(stream()))
           .withTimestampExtractor(lambda t: t["ts"])
           .withOutputBatchSize(batch).build())
    snk = wf.Sink_Builder(acc).withParallelism(sink_parallelism).build()
    g = wf.PipeGraph("win", mode, wf.TimePolicy.EVENT)
    g.add_source(src).add(win_op).add_sink(snk)
    g.run()
    return acc


WIN, SLIDE = 16, 4          # count windows
TWIN, TSLIDE = 16_000, 4_000  # time windows (µs)


@pytest.mark.parametrize("mode", [wf.ExecutionMode.DEFAULT,
                                  wf.ExecutionMode.DETERMINISTIC])
def test_keyed_windows_cb(mode):
    rnd = random.Random(5)
    exp = oracle_cb(WIN, SLIDE)
    for incremental_fn in [lambda items: sum(t["value"] for t in items),
                           lambda t, acc: (acc or 0) + t["value"]]:
        for _ in range(3):
            op = (wf.Keyed_Windows_Builder(incremental_fn)
                  .withCBWindows(WIN, SLIDE)
                  .withKeyBy(lambda t: t["key"])
                  .withParallelism(rnd.randint(1, 3)).build())
            acc = run_graph(op, rnd.randint(1, 16), mode)
            assert (acc.count, acc.total) == exp


@pytest.mark.parametrize("mode", [wf.ExecutionMode.DEFAULT,
                                  wf.ExecutionMode.DETERMINISTIC])
def test_keyed_windows_tb(mode):
    rnd = random.Random(6)
    exp = oracle_tb(TWIN, TSLIDE)
    for _ in range(3):
        op = (wf.Keyed_Windows_Builder(
                lambda items: sum(t["value"] for t in items))
              .withTBWindows(TWIN, TSLIDE)
              .withKeyBy(lambda t: t["key"])
              .withParallelism(rnd.randint(1, 3)).build())
        acc = run_graph(op, rnd.randint(1, 16), mode)
        assert (acc.count, acc.total) == exp


def test_parallel_windows_cb_tb():
    rnd = random.Random(7)
    for _ in range(3):
        op = (wf.Parallel_Windows_Builder(
                lambda items: sum(t["value"] for t in items))
              .withCBWindows(WIN, SLIDE)
              .withKeyBy(lambda t: t["key"])
              .withParallelism(rnd.randint(1, 3)).build())
        acc = run_graph(op, rnd.randint(1, 16))
        assert (acc.count, acc.total) == oracle_cb(WIN, SLIDE)
    for _ in range(3):
        op = (wf.Parallel_Windows_Builder(
                lambda items: sum(t["value"] for t in items))
              .withTBWindows(TWIN, TSLIDE)
              .withKeyBy(lambda t: t["key"])
              .withParallelism(rnd.randint(1, 3)).build())
        acc = run_graph(op, rnd.randint(1, 16))
        assert (acc.count, acc.total) == oracle_tb(TWIN, TSLIDE)


def test_paned_windows_cb_tb():
    rnd = random.Random(8)
    plq = lambda items: sum(t["value"] for t in items)
    wlq = lambda panes: sum(panes)
    for _ in range(2):
        op = (wf.Paned_Windows_Builder(plq, wlq)
              .withCBWindows(WIN, SLIDE)
              .withKeyBy(lambda t: t["key"])
              .withParallelisms(rnd.randint(1, 3), rnd.randint(1, 3)).build())
        acc = run_graph(op, rnd.randint(1, 16))
        assert (acc.count, acc.total) == oracle_cb(WIN, SLIDE)
    for _ in range(2):
        op = (wf.Paned_Windows_Builder(plq, wlq)
              .withTBWindows(TWIN, TSLIDE)
              .withKeyBy(lambda t: t["key"])
              .withParallelisms(rnd.randint(1, 3), rnd.randint(1, 3)).build())
        acc = run_graph(op, rnd.randint(1, 16))
        assert (acc.count, acc.total) == oracle_tb(TWIN, TSLIDE)


def test_mapreduce_windows_cb_tb():
    rnd = random.Random(9)
    map_fn = lambda items: sum(t["value"] for t in items)
    red_fn = lambda partials: sum(partials)
    for _ in range(2):
        op = (wf.MapReduce_Windows_Builder(map_fn, red_fn)
              .withCBWindows(WIN, SLIDE)
              .withKeyBy(lambda t: t["key"])
              .withParallelisms(rnd.randint(1, 3), rnd.randint(1, 3)).build())
        acc = run_graph(op, rnd.randint(1, 16))
        assert (acc.count, acc.total) == oracle_cb(WIN, SLIDE)
    for _ in range(2):
        op = (wf.MapReduce_Windows_Builder(map_fn, red_fn)
              .withTBWindows(TWIN, TSLIDE)
              .withKeyBy(lambda t: t["key"])
              .withParallelisms(rnd.randint(1, 3), rnd.randint(1, 3)).build())
        acc = run_graph(op, rnd.randint(1, 16))
        assert (acc.count, acc.total) == oracle_tb(TWIN, TSLIDE)


def test_ffat_windows_cb_tb():
    rnd = random.Random(10)
    lift = lambda t: t["value"]
    comb = lambda a, b: a + b
    for _ in range(3):
        op = (wf.Ffat_Windows_Builder(lift, comb)
              .withCBWindows(WIN, SLIDE)
              .withKeyBy(lambda t: t["key"])
              .withParallelism(rnd.randint(1, 3)).build())
        acc = run_graph(op, rnd.randint(1, 16))
        assert (acc.count, acc.total) == oracle_cb(WIN, SLIDE)
    for _ in range(3):
        op = (wf.Ffat_Windows_Builder(lift, comb)
              .withTBWindows(TWIN, TSLIDE)
              .withKeyBy(lambda t: t["key"])
              .withParallelism(rnd.randint(1, 3)).build())
        acc = run_graph(op, rnd.randint(1, 16))
        assert (acc.count, acc.total) == oracle_tb(TWIN, TSLIDE)


def test_ffat_windows_non_invertible():
    """FlatFAT works for non-invertible combiners (max), unlike
    subtract-based sliding sums."""
    lift = lambda t: t["value"]
    comb = max
    op = (wf.Ffat_Windows_Builder(lift, comb)
          .withCBWindows(WIN, SLIDE)
          .withKeyBy(lambda t: t["key"]).build())
    got = []
    src = (wf.Source_Builder(lambda: iter(stream()))
           .withOutputBatchSize(8).build())
    snk = wf.Sink_Builder(
        lambda r: got.append((r.key, r.wid, r.value))
        if r is not None else None).build()
    g = wf.PipeGraph("ffmax", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(op).add_sink(snk)
    g.run()
    per_key = {}
    for t in stream():
        per_key.setdefault(t["key"], []).append(t["value"])
    exp = {}
    for k, vals in per_key.items():
        w = 0
        while w * SLIDE < len(vals):
            exp[(k, w)] = max(vals[w * SLIDE: w * SLIDE + WIN])
            w += 1
    assert dict(((k, w), v) for k, w, v in got) == exp


def test_ffat_tpu_cb():
    """FfatWindowsTPU vs the host oracle (reference win_tests_gpu pattern:
    accelerator windows must reproduce host results)."""
    exp = oracle_cb(WIN, SLIDE)
    for batch in (32, 64):
        acc = WinAcc()
        src = (wf.Source_Builder(lambda: iter(stream()))
               .withOutputBatchSize(batch).build())
        op = (wf.Ffat_WindowsTPU_Builder(
                lambda t: t["value"], lambda a, b: a + b)
              .withCBWindows(WIN, SLIDE)
              .withKeyBy(lambda t: t["key"])
              .withMaxKeys(N_KEYS).build())
        snk = wf.Sink_Builder(
            lambda r: acc(_as_result(r)) if r is not None else None).build()
        g = wf.PipeGraph("ffat_tpu", wf.ExecutionMode.DEFAULT)
        g.add_source(src).add(op).add_sink(snk)
        g.run()
        assert (acc.count, acc.total) == exp


def _as_result(rec):
    return wf.WindowResult(rec["key"], rec["wid"], rec["value"])


def test_flatfat_structure():
    """FlatFAT unit check against naive range folds (reference flatfat.hpp)."""
    import operator
    rnd = random.Random(11)
    fat = wf.FlatFAT(operator.add, 16)
    vals = []
    for pos in range(50):
        v = rnd.randint(0, 100)
        vals.append(v)
        fat.update(pos, v)
        lo = max(0, pos - 15)
        assert fat.query(lo, pos + 1) == sum(vals[lo:pos + 1])
        for old in range(max(0, pos - 15)):
            fat.evict(old)


def test_tb_boundary_ties_ordered_mode():
    """Regression: in ordered modes, tuples sharing the frontier timestamp
    must all land in their window — a window ending at ts+1 may not fire
    until a strictly later timestamp arrives."""
    items = [{"k": 0, "v": "a", "ts": 5}, {"k": 0, "v": "b", "ts": 9},
             {"k": 0, "v": "c", "ts": 9}, {"k": 0, "v": "d", "ts": 12}]
    for build in [
        lambda: (wf.Keyed_Windows_Builder(lambda its: len(its))
                 .withTBWindows(10, 10).withKeyBy(lambda t: t["k"]).build()),
        lambda: (wf.Ffat_Windows_Builder(lambda t: 1, lambda a, b: a + b)
                 .withTBWindows(10, 10).withKeyBy(lambda t: t["k"]).build()),
    ]:
        got = []
        src = (wf.Source_Builder(lambda: iter(items))
               .withTimestampExtractor(lambda t: t["ts"])
               .withOutputBatchSize(1).build())
        snk = wf.Sink_Builder(
            lambda r: got.append((r.wid, r.value))
            if r is not None else None).build()
        g = wf.PipeGraph("ties", wf.ExecutionMode.DETERMINISTIC,
                         wf.TimePolicy.EVENT)
        g.add_source(src).add(build()).add_sink(snk)
        g.run()
        assert sorted(got) == [(0, 3), (1, 1)], got


def test_ffat_tpu_parallelism_no_duplicate_flush():
    """Regression: multiple FfatWindowsTPU replicas share one logical state
    table; EOS must flush it exactly once."""
    exp = oracle_cb(WIN, SLIDE)
    acc = WinAcc()
    src = (wf.Source_Builder(lambda: iter(stream()))
           .withOutputBatchSize(64).build())
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                     lambda a, b: a + b)
          .withCBWindows(WIN, SLIDE).withKeyBy(lambda t: t["key"])
          .withMaxKeys(N_KEYS).withParallelism(2).build())
    snk = wf.Sink_Builder(
        lambda r: acc(_as_result(r)) if r is not None else None).build()
    g = wf.PipeGraph("ffat_tpu_p2", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(op).add_sink(snk)
    g.run()
    assert (acc.count, acc.total) == exp


def test_ffat_tpu_tb():
    """Time-based FfatWindowsTPU (quantum panes + watermark firing) vs the
    host oracle (reference win_tests_gpu are TB-only:
    ``test_win_fat_gpu_tb.cpp``), swept over batch capacities including
    ones that straddle pane boundaries."""
    exp = oracle_tb(TWIN, TSLIDE)
    for batch in (1, 7, 16, 64, 256):
        acc = WinAcc()
        src = (wf.Source_Builder(lambda: iter(stream()))
               .withTimestampExtractor(lambda t: t["ts"])
               .withOutputBatchSize(batch).build())
        op = (wf.Ffat_WindowsTPU_Builder(
                lambda t: t["value"], lambda a, b: a + b)
              .withTBWindows(TWIN, TSLIDE)
              .withKeyBy(lambda t: t["key"])
              .withMaxKeys(N_KEYS).build())
        snk = wf.Sink_Builder(
            lambda r: acc(_as_result(r)) if r is not None else None).build()
        g = wf.PipeGraph("ffat_tpu_tb", wf.ExecutionMode.DEFAULT,
                         wf.TimePolicy.EVENT)
        g.add_source(src).add(op).add_sink(snk)
        g.run()
        assert (acc.count, acc.total) == exp, f"batch={batch}"


@pytest.mark.slow   # ring-policy soak: nightly leg (calibration-round headroom pass)
def test_ffat_tpu_tb_small_ring_and_lateness():
    """A tight pane ring still produces exact results when batches arrive in
    order (ring >= window span + batch time spread), and lateness delays
    firing without changing totals."""
    exp = oracle_tb(TWIN, TSLIDE)
    # 32-tuple batches span 8 panes (1 ms tuples, 4 ms panes); R = 4
    for pane_cap, lateness in ((13, 0), (16, 2_000)):
        acc = WinAcc()
        src = (wf.Source_Builder(lambda: iter(stream()))
               .withTimestampExtractor(lambda t: t["ts"])
               .withOutputBatchSize(32).build())
        b = (wf.Ffat_WindowsTPU_Builder(
                lambda t: t["value"], lambda a, b: a + b)
             .withTBWindows(TWIN, TSLIDE)
             .withKeyBy(lambda t: t["key"])
             .withMaxKeys(N_KEYS).withPaneCapacity(pane_cap))
        if lateness:
            b = b.withLateness(lateness)
        op = b.build()
        snk = wf.Sink_Builder(
            lambda r: acc(_as_result(r)) if r is not None else None).build()
        g = wf.PipeGraph("ffat_tpu_tb2", wf.ExecutionMode.DEFAULT,
                         wf.TimePolicy.EVENT)
        g.add_source(src).add(op).add_sink(snk)
        g.run()
        assert (acc.count, acc.total) == exp, (pane_cap, lateness)


def _jittered_stream(jitter_us, seed=21):
    rnd = random.Random(seed)
    out = []
    for i in range(LENGTH):
        ts = max(0, i * 1000 + rnd.randint(-jitter_us, jitter_us))
        out.append({"key": i % N_KEYS, "value": i, "ts": ts})
    return out


def _oracle_tb_items(items, win_us, slide_us):
    per_key = {}
    for t in items:
        per_key.setdefault(t["key"], []).append((t["ts"], t["value"]))
    exp = {}
    for k, pts in per_key.items():
        wids = set()
        for ts, _ in pts:
            last = ts // slide_us
            first = max(0, -(-(ts - win_us + 1) // slide_us))
            wids.update(range(first, last + 1))
        for w in wids:
            vals = [v for ts, v in pts
                    if w * slide_us <= ts < w * slide_us + win_us]
            if vals:
                exp[(k, w)] = sum(vals)
    return exp


def _run_ffat_tpu_tb(items, lateness):
    got = {}
    src = (wf.Source_Builder(lambda: iter(items))
           .withTimestampExtractor(lambda t: t["ts"])
           .withOutputBatchSize(32).build())
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                     lambda a, b: a + b)
          .withTBWindows(TWIN, TSLIDE).withKeyBy(lambda t: t["key"])
          .withMaxKeys(N_KEYS).withLateness(lateness).build())
    snk = wf.Sink_Builder(
        lambda r: got.__setitem__((r["key"], r["wid"]), r["value"])
        if r is not None else None).build()
    g = wf.PipeGraph("ffat_tpu_ooo", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    g.add_source(src).add(op).add_sink(snk)
    g.run()
    return got, op


def test_ffat_tpu_tb_out_of_order():
    """Disorder within the lateness bound: exact results, nothing dropped.
    The host Ffat_Windows under the same feed is the reference result
    (reference win_tests_gpu oracle style)."""
    items = _jittered_stream(2000)
    got, op = _run_ffat_tpu_tb(items, lateness=2500)
    exp = _oracle_tb_items(items, TWIN, TSLIDE)
    assert got == exp
    st = op.dump_stats()
    assert st["Late_tuples_dropped"] == 0


@pytest.mark.slow   # ring-policy soak: nightly leg (calibration-round headroom pass)
def test_ffat_tpu_tb_watermark_jump():
    """An idle gap far wider than the pane ring (watermark jumps hundreds of
    panes between batches): pre-gap windows fire exactly before the ring
    rolls forward — nothing is evicted or dropped.  The gap lands on a batch
    boundary; a batch whose own tuples straddle a gap wider than the ring is
    overload by the ring contract (pane_capacity >= window span + batch time
    spread) and is exercised below with a contract-sized ring."""
    gap = 1_000_000  # 250 panes of 4 ms; ring default is R + 64 = 68
    items = []
    for i in range(LENGTH):
        ts = i * 1000 + (gap if i >= 192 else 0)   # 192 % 16 == 192 % 64 == 0
        items.append({"key": i % N_KEYS, "value": i, "ts": ts})
    exp = _oracle_tb_items(items, TWIN, TSLIDE)
    for batch, pane_cap in ((16, None), (64, None), (64, 280)):
        # pane_cap=280 > gap span: the same jump *inside* one batch is exact
        # when the ring is sized to the batch's time spread (the contract);
        # with batch=64 and the gap at 192 every batch is one-sided anyway,
        # so run the straddling variant by shifting the gap off-boundary
        shifted = pane_cap is not None
        data = items if not shifted else [
            {"key": t["key"], "value": t["value"],
             "ts": t["value"] * 1000 + (gap if t["value"] >= 200 else 0)}
            for t in items]
        got = {}
        src = (wf.Source_Builder(lambda: iter(data))
               .withTimestampExtractor(lambda t: t["ts"])
               .withOutputBatchSize(batch).build())
        b = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                        lambda a, b: a + b)
             .withTBWindows(TWIN, TSLIDE).withKeyBy(lambda t: t["key"])
             .withMaxKeys(N_KEYS))
        if pane_cap:
            b = b.withPaneCapacity(pane_cap)
        op = b.build()
        snk = wf.Sink_Builder(
            lambda r: got.__setitem__((r["key"], r["wid"]), r["value"])
            if r is not None else None).build()
        g = wf.PipeGraph("ffat_tpu_jump", wf.ExecutionMode.DEFAULT,
                         wf.TimePolicy.EVENT)
        g.add_source(src).add(op).add_sink(snk)
        g.run()
        want = exp if not shifted else _oracle_tb_items(data, TWIN, TSLIDE)
        assert got == want, f"batch={batch} pane_cap={pane_cap}"
        st = op.dump_stats()
        assert st["Late_tuples_dropped"] == 0
        assert st["Pane_cells_evicted"] == 0


def test_ffat_tb_kernel_stalled_then_jumping_watermark():
    """Kernel-level: the watermark stalls while data fills the ring to its
    edge, then jumps past everything.  The two pre-place fire passes must
    fire every in-ring window (the first pass's roll brings ring-end window
    ends in range for the second) before the capacity roll would evict
    them."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from windflow_tpu.windows.ffat_kernels import (make_ffat_tb_state,
                                                   make_ffat_tb_step)

    K, P_usec, R, D, NP, cap = 1, 1000, 4, 1, 16, 8
    step = jax.jit(make_ffat_tb_step(cap, K, P_usec, R, D, NP,
                                     lambda t: t["v"], lambda a, b: a + b,
                                     None))
    state = make_ffat_tb_state(jnp.zeros((), jnp.int64), K, NP)
    fired_windows = {}

    def run(state, tss, wm_pane):
        payload = {"v": jnp.asarray(tss, jnp.int64)}
        ts = jnp.asarray(tss, jnp.int64)
        valid = jnp.ones(cap, bool)
        state, out, fired, _, _ = step(state, payload, ts, valid,
                                       jnp.int64(wm_pane))
        f = np.asarray(fired)
        for i in np.nonzero(f)[0]:
            wid = int(np.asarray(out["wid"])[i])
            assert wid not in fired_windows, f"duplicate window {wid}"
            fired_windows[wid] = int(np.asarray(out["value"])[i])
        return state

    # two batches fill panes 0..15 (one tuple per pane), watermark stalled
    state = run(state, [i * 1000 for i in range(8)], wm_pane=0)
    state = run(state, [i * 1000 for i in range(8, 16)], wm_pane=0)
    # next batch sits far ahead; watermark jumps with it.  Every window over
    # panes 0..15 must fire (ends 4..16 span more than one ring length past
    # base, requiring both pre-place passes), nothing evicted.
    state = run(state, [1_000_000 + i * 1000 for i in range(8)],
                wm_pane=2000)
    assert int(state["n_evicted"]) == 0
    assert int(state["n_late"]) == 0
    for w in range(0, 13):   # windows [w, w+4) fully inside panes 0..15
        exp = sum(p * 1000 for p in range(w, w + 4))
        assert fired_windows.get(w) == exp, (w, fired_windows.get(w))


def test_ffat_tpu_tb_late_drops_counted():
    """Disorder beyond the lateness bound: late tuples (panes already
    rolled out by firing) are dropped AND surfaced in the stats."""
    rnd = random.Random(33)
    items = []
    for i in range(LENGTH):
        ts = i * 1000
        if i % 40 == 39:
            ts = max(0, ts - 60_000)   # very late stragglers
        items.append({"key": i % N_KEYS, "value": i, "ts": ts})
    got, op = _run_ffat_tpu_tb(items, lateness=0)
    st = op.dump_stats()
    assert st["Late_tuples_dropped"] > 0
    # on-time data is still exact for windows without stragglers
    exp_on_time = _oracle_tb_items(
        [t for t in items if t["value"] % 40 != 39], TWIN, TSLIDE)
    on_time_ok = sum(1 for kk, v in exp_on_time.items()
                     if got.get(kk) == v)
    assert on_time_ok > 0.8 * len(exp_on_time)


@pytest.mark.slow   # ring-policy soak: nightly leg (calibration-round headroom pass)
def test_ffat_tpu_tb_overflow_policies():
    """TB ring overflow (one batch spanning far more panes than the ring):
    'drop' (default) suppresses windows that lost data and counts them —
    every window that IS emitted is exact; 'count' fires them over surviving
    panes (wrong aggregates, evictions counted); 'error' raises."""
    P = 4_000
    items = [{"key": 0, "value": i, "ts": i * P} for i in range(40)]
    exp = _oracle_tb_items(items, TWIN, TSLIDE)   # R=4, D=1

    def run(policy):
        # lateness of 60 panes >> the 8-pane ring pins windows open while
        # data keeps arriving: the capacity roll must evict unfired data
        got = {}
        src = (wf.Source_Builder(lambda: iter(items))
               .withTimestampExtractor(lambda t: t["ts"])
               .withOutputBatchSize(8).build())
        op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                         lambda a, b: a + b)
              .withTBWindows(TWIN, TSLIDE).withKeyBy(lambda t: t["key"])
              .withMaxKeys(1).withPaneCapacity(8).withLateness(240_000)
              .withOverflowPolicy(policy).build())
        snk = wf.Sink_Builder(
            lambda r: got.__setitem__((r["key"], r["wid"]), r["value"])
            if r is not None else None).build()
        g = wf.PipeGraph("tb_overflow", wf.ExecutionMode.DEFAULT,
                         wf.TimePolicy.EVENT)
        g.add_source(src).add(op).add_sink(snk)
        g.run()
        return got, op.dump_stats()

    got, st = run("drop")
    assert st["Pane_cells_evicted"] > 0
    assert st["Windows_dropped_on_overflow"] > 0
    assert all(exp[kw] == v for kw, v in got.items())   # emitted => exact
    assert len(got) < len(exp)                          # some suppressed

    got_c, st_c = run("count")
    assert st_c["Pane_cells_evicted"] > 0
    assert st_c["Windows_dropped_on_overflow"] == 0
    assert any(exp.get(kw) != v for kw, v in got_c.items())  # wrong fires

    import pytest
    with pytest.raises(wf.WindFlowError, match="overflow"):
        run("error")


def test_ffat_tpu_tb_forward_parallelism_rejected():
    """Non-keyed (FORWARD-routed) TB windows cannot scale by replication:
    round-robin would interleave batches into the shared pane ring in
    replica-drain order, not arrival order.  The builder rejects it; keyed
    routing (withKeyBy) is the scaling path."""
    with pytest.raises(wf.WindFlowError, match="parallelism == 1"):
        (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                    lambda a, b: a + b)
         .withTBWindows(8_000, 8_000).withMaxKeys(1)
         .withParallelism(2).build())

    # parallelism == 1 non-keyed TB works and is exact
    items = [{"value": i, "ts": i * 1000} for i in range(60)]
    got = {}
    src = (wf.Source_Builder(lambda: iter(items))
           .withTimestampExtractor(lambda t: t["ts"])
           .withOutputBatchSize(5).build())
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                     lambda a, b: a + b)
          .withTBWindows(8_000, 8_000).withMaxKeys(1).build())
    snk = wf.Sink_Builder(
        lambda r: got.__setitem__((r["key"], r["wid"]), r["value"])
        if r is not None else None).build()
    g = wf.PipeGraph("tb_fwd", wf.ExecutionMode.DEFAULT, wf.TimePolicy.EVENT)
    g.add_source(src).add(op).add_sink(snk)
    g.run()
    exp = {}
    for t in items:
        w = t["ts"] // 8_000
        exp[(0, w)] = exp.get((0, w), 0) + t["value"]
    assert got == exp


@pytest.mark.slow   # ring-policy soak: nightly leg (calibration-round headroom pass)
def test_ffat_tpu_tb_ring_regrows_on_overflow():
    """An auto-sized TB pane ring whose first batch under-represents the
    steady state (dense burst, then 1 tuple per pane) must GROW to the
    batch-spread contract.  Since the r5 span regrow (DeviceBatch.ts_max
    vs the watermark frontier, checked host-side before every step) the
    growth is PREEMPTIVE: the ring resizes before the capacity roll can
    evict anything, so every window of the whole stream is exact and the
    eviction counter stays zero (previously this scenario evicted first
    and was exact only after the post-hoc regrow)."""
    batch, P_usec = 512, 4_000   # win 16 ms / slide 4 ms -> R=4, D=1
    items = []
    for i in range(batch):       # batch 1: all inside one pane
        items.append({"key": 0, "value": 1, "ts": i})
    n_batches = 140
    for j in range(n_batches * batch):  # then exactly 1 tuple per pane
        items.append({"key": 0, "value": 1,
                      "ts": (j + 1) * P_usec})
    got = {}
    src = (wf.Source_Builder(lambda: iter(items))
           .withTimestampExtractor(lambda t: t["ts"])
           .withOutputBatchSize(batch).build())
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                     lambda a, b: a + b)
          .withTBWindows(16_000, 4_000).withMaxKeys(1).build())
    snk = wf.Sink_Builder(
        lambda r: got.__setitem__(int(r["wid"]), int(r["value"]))
        if r is not None else None).build()
    g = wf.PipeGraph("regrow", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    g.add_source(src).add(op).add_sink(snk)
    g.run()
    st = op.dump_stats()
    # the span regrow resized the ring BEFORE any eviction: nothing was
    # lost, and the ring covers the per-batch pane spread
    assert st["Pane_cells_evicted"] == 0
    assert op.NP >= batch, op.NP
    # EVERY full window of the steady stream is exact (each covers
    # 4 panes x 1 tuple = 4), not just the post-growth tail
    last_pane = n_batches * batch
    for w in range(4, last_pane - 4):
        assert got.get(w) == 4, (w, got.get(w))


@pytest.mark.slow   # ring-policy soak: nightly leg (calibration-round headroom pass)
def test_ffat_tpu_tb_auto_ring_error_policy_grows_not_raises():
    """overflow_policy='error' with an AUTO-sized ring: the preemptive
    span regrow resizes before anything could evict, so the policy never
    fires (a user-sized ring still errors as before)."""
    batch, P_usec = 256, 4_000
    items = [{"key": 0, "value": 1, "ts": i} for i in range(batch)]
    for j in range(80 * batch):
        items.append({"key": 0, "value": 1, "ts": (j + 1) * P_usec})
    src = (wf.Source_Builder(lambda: iter(items))
           .withTimestampExtractor(lambda t: t["ts"])
           .withOutputBatchSize(batch).build())
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                     lambda a, b: a + b)
          .withTBWindows(16_000, 4_000).withMaxKeys(1)
          .withOverflowPolicy("error").build())
    snk = wf.Sink_Builder(lambda r: None).build()
    g = wf.PipeGraph("regrow_err", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    g.add_source(src).add(op).add_sink(snk)
    g.run()   # must not raise: growth, not error
    assert op.NP >= batch, op.NP
    assert op.dump_stats()["Pane_cells_evicted"] == 0


def test_ffat_tpu_cb_sum_combiner_fast_path():
    """withSumCombiner (flagless CB sliding fold) is bitwise-identical to
    the default flag-aware fold on integer sums, single-chip and mesh."""
    exp = oracle_cb(WIN, SLIDE)
    for batch in (32, 64):
        acc = WinAcc()
        src = (wf.Source_Builder(lambda: iter(stream()))
               .withOutputBatchSize(batch).build())
        op = (wf.Ffat_WindowsTPU_Builder(
                lambda t: t["value"], lambda a, b: a + b)
              .withCBWindows(WIN, SLIDE)
              .withKeyBy(lambda t: t["key"])
              .withMaxKeys(N_KEYS).withSumCombiner().build())
        snk = wf.Sink_Builder(
            lambda r: acc(_as_result(r)) if r is not None else None).build()
        g = wf.PipeGraph("ffat_sum", wf.ExecutionMode.DEFAULT)
        g.add_source(src).add(op).add_sink(snk)
        g.run()
        assert (acc.count, acc.total) == exp, batch


def test_ffat_tpu_sum_combiner_tb_scatter_add_path():
    """withSumCombiner on TB windows takes the sort-free scatter-add
    placement (r5): results must match the grouped path's against the
    oracle.  (Until r5 this combination only warned as a no-op.)"""
    stream = [{"key": i % 3, "value": i, "ts": i * 1000}
              for i in range(240)]
    from conftest import tb_window_sums
    per_key = {}
    for t in stream:
        per_key.setdefault(t["key"], []).append((t["ts"], t["value"]))
    exp = tb_window_sums(per_key, 16_000, 4_000)
    for declare in (False, True):
        got = {}
        src = (wf.Source_Builder(lambda: iter(stream))
               .withTimestampExtractor(lambda t: t["ts"])
               .withOutputBatchSize(31).build())
        b = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                        lambda a, b: a + b)
             .withKeyBy(lambda t: t["key"]).withMaxKeys(3)
             .withTBWindows(16_000, 4_000))
        if declare:
            b = b.withSumCombiner()
        snk = wf.Sink_Builder(
            lambda r: got.__setitem__((r["key"], r["wid"]), r["value"])
            if r is not None else None).build()
        g = wf.PipeGraph("ffat_tb_sum", wf.ExecutionMode.DEFAULT,
                         wf.TimePolicy.EVENT)
        g.add_source(src).add(b.build()).add_sink(snk)
        g.run()
        assert got == exp, (declare, len(got), len(exp))


@pytest.mark.slow   # ring-policy soak: nightly leg (calibration-round headroom pass)
def test_ffat_tpu_tb_ring_grows_under_merged_channel_lag():
    """The fuzz-found eviction class (r5, 5000-tuple soak seeds
    8019/8034) distilled: two merged sources where one runs ~200 panes
    ahead of the other — the min-folded watermark tracks the laggard, so
    the leader's panes pin in the ring far beyond the first-batch
    estimate AND beyond the old batch-capacity ring ceiling.  The
    ts_max-vs-frontier span regrow must grow the ring preemptively:
    zero evictions, zero suppressed windows, results exactly the
    single-source oracle."""
    from conftest import tb_window_sums
    N, LEAD = 600, 200_000
    a = [{"key": 0, "value": i, "ts": i * 1000 + LEAD} for i in range(N)]
    b = [{"key": 1, "value": i, "ts": i * 1000} for i in range(N)]
    got = {}
    g = wf.PipeGraph("lag_merge", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    mp = g.add_source(
        wf.Source_Builder(lambda: iter(a))
        .withTimestampExtractor(lambda t: t["ts"])
        .withOutputBatchSize(16).build())
    mp2 = g.add_source(
        wf.Source_Builder(lambda: iter(b))
        .withTimestampExtractor(lambda t: t["ts"])
        .withOutputBatchSize(16).build())
    mp = mp.merge(mp2)
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                     lambda a_, b_: a_ + b_)
          .withTBWindows(4_000, 1_000).withKeyBy(lambda t: t["key"])
          .withMaxKeys(2).build())
    mp.add(op).add_sink(wf.Sink_Builder(
        lambda r: got.__setitem__((r["key"], r["wid"]), r["value"])
        if r is not None else None).build())
    g.run()
    st = op.dump_stats()
    assert st["Pane_cells_evicted"] == 0, st
    assert st["Windows_dropped_on_overflow"] == 0, st
    assert st["Late_tuples_dropped"] == 0, st
    assert op.NP > 200, op.NP   # grew to cover the lag, not just R+64
    per_key = {0: [(t["ts"], t["value"]) for t in a],
               1: [(t["ts"], t["value"]) for t in b]}
    assert got == tb_window_sums(per_key, 4_000, 1_000)


def test_ffat_tpu_tb_auto_ring_defers_ceiling_until_fold_resolves():
    """ADVICE r5 low (windows/ffat_tpu.py _regrow_for_span): batches
    staged before the multi-channel watermark fold resolves carry
    ``frontier == WM_NONE``; the old path grew straight to the memory
    ceiling — permanently charging tiny-span streams a ceiling-size ring
    plus a step recompile.  The deferral grows only to the OBSERVED
    pre-fold data spread; a small-span merged stream must finish with a
    small ring, exact results, and nothing evicted."""
    from conftest import tb_window_sums
    N = 400
    a = [{"key": 0, "value": i, "ts": i * 1000} for i in range(N)]
    b = [{"key": 1, "value": i, "ts": i * 1000} for i in range(N)]
    got = {}
    g = wf.PipeGraph("fold_defer", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    mp = g.add_source(
        wf.Source_Builder(lambda: iter(a))
        .withTimestampExtractor(lambda t: t["ts"])
        .withOutputBatchSize(16).build())
    mp2 = g.add_source(
        wf.Source_Builder(lambda: iter(b))
        .withTimestampExtractor(lambda t: t["ts"])
        .withOutputBatchSize(16).build())
    mp = mp.merge(mp2)
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                     lambda a_, b_: a_ + b_)
          .withTBWindows(4_000, 1_000).withKeyBy(lambda t: t["key"])
          .withMaxKeys(2).build())
    mp.add(op).add_sink(wf.Sink_Builder(
        lambda r: got.__setitem__((r["key"], r["wid"]), r["value"])
        if r is not None else None).build())
    g.run()
    st = op.dump_stats()
    assert st["Pane_cells_evicted"] == 0, st
    assert st["Late_tuples_dropped"] == 0, st
    # the WM_NONE phase no longer commits the ceiling: the ring stays
    # sized to the observed span, far under the memory bound
    assert op._np_ceil >= 4096, op._np_ceil   # bound is real headroom
    assert op.NP <= op._np_ceil // 4, (op.NP, op._np_ceil)
    per_key = {0: [(t["ts"], t["value"]) for t in a],
               1: [(t["ts"], t["value"]) for t in b]}
    assert got == tb_window_sums(per_key, 4_000, 1_000)


def test_ffat_tpu_tb_span_regrow_skipped_multi_host(monkeypatch):
    """ADVICE r5 medium: the span regrow reads host-side batch ts extrema,
    which on a multi-host mesh are process-LOCAL — divergent growth
    decisions would desynchronize the sharded ring shapes across
    processes.  With process_count > 1 the span regrow must be a no-op
    (the SPMD-consistent eviction-cadence regrow stays the growth path)."""
    import types
    import jax
    items = [{"key": 0, "value": 1, "ts": i * 1000} for i in range(64)]
    src = (wf.Source_Builder(lambda: iter(items))
           .withTimestampExtractor(lambda t: t["ts"])
           .withOutputBatchSize(16).build())
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                     lambda a, b: a + b)
          .withTBWindows(8_000, 2_000).withMaxKeys(1).build())
    snk = wf.Sink_Builder(lambda r: None).build()
    g = wf.PipeGraph("mh_skip", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    g.add_source(src).add(op).add_sink(snk)
    g.run()                                # initialize ring + auto sizing
    np0 = op.NP
    assert op._auto_np and np0 < op._np_ceil
    wide = types.SimpleNamespace(
        frontier=64_000, ts_min=64_000,
        ts_max=64_000 + op.P * (np0 + 512))   # would force growth locally
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    op._regrow_for_span(wide)
    assert op.NP == np0                    # skipped: no divergent growth
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    op._regrow_for_span(wide)
    assert op.NP > np0                     # same batch grows single-host
