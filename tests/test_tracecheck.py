"""wfverify (windflow_tpu/analysis/tracecheck.py): object-level static
trace-safety, determinism and donation verification of the live kernel
objects.

One seeded-violation fixture per WFxxx code (caught with the exact code
anchored to this file) plus a clean twin (zero diagnostics), the inline
suppression contract (honored with a reason, rejected without), the
``tools/wf_verify.py`` CLI JSON round trip, the preflight integration
(``check()`` surfaces WF8xx next to the WF1xx-WF6xx table), and the
static/dynamic cross-validation: the seeded determinism-violating chaos
family (``durability/chaos.py`` "wallclock") is flagged WF612 by
wfverify on the same graph whose chaos A/B diff fails dynamically —
expected-fail-dynamic, caught-static.
"""

import dataclasses
import json
import os
import random as _random
import subprocess
import sys
import time as _time

import jax.numpy as jnp
import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.analysis import tracecheck as tc
from windflow_tpu.analysis.diagnostics import CODES, PreflightError
from windflow_tpu.monitoring.jit_registry import wf_jit

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
THIS = os.path.basename(__file__)


def codes(findings):
    return [f.code for f in findings]


# ---------------------------------------------------------------------------
# seeded fixtures: one violating kernel + clean twin per code
# ---------------------------------------------------------------------------

def k_clean(t):
    return {"k": t["k"], "v": t["v"] * 2.0}


def k_wf801(t):
    return {"k": t["k"], "v": float(t["v"]) + 1.0}


def k_wf801_np(t):
    return {"k": t["k"], "v": np.asarray(t["v"]) + 1.0}


def k_wf802(t):
    if t["v"] > 0:
        return {"k": t["k"], "v": t["v"]}
    return {"k": t["k"], "v": -t["v"]}


def k_wf802_clean(t):
    # is-None / membership / shape reads are Python-level: never flagged
    extra = t["x"] if "x" in t else t["v"]
    assert extra is not None
    return {"k": t["k"], "v": jnp.where(t["v"] > 0, t["v"], -t["v"])}


_ACC = []


def k_wf803(t):
    _ACC.append(t)
    return t


def k_wf803_local(t):
    local = []                   # local containers are fine
    local.append(t["v"])
    return {"k": t["k"], "v": local[0]}


def k_wf804(t):
    print("saw", t)
    return t


_BUF = [1.0, 2.0, 3.0]


def k_wf811(t):
    return {"k": t["k"], "v": t["v"] * len(_BUF)}


_FROZEN = (1.0, 2.0, 3.0)


def k_wf811_clean(t):
    # len() of an immutable closure tuple cannot vary per call
    return {"k": t["k"], "v": t["v"] * len(_FROZEN)}


def k_wf812(p, v):
    return {"k": p["k"], "v": jnp.nonzero(p["v"])[0].astype(jnp.float32)}


def k_wf812_mask(p, v):
    return {"k": p["k"], "v": p["v"][p["v"] > 0]}


def k_wf812_clean(p, v):
    return {"k": p["k"], "v": jnp.where(p["v"] > 0, p["v"], 0.0)}


def k_wf612(t):
    return {"k": t["k"], "v": t["v"] + _time.time()}


def s_wf611(r):
    if r is None:
        return
    _ = _random.random()


def s_wf611_clean(r):
    if r is None:
        return
    _ = sorted([1, 2, 3])


def s_wf613_id(r):
    if r is None:
        return
    _ = id(r)


def s_wf613_hash(r):
    if r is None:
        return
    _ = hash("bucket")


_KEYSET = {"a", "b", "c"}


def s_wf614(r):
    if r is None:
        return
    for k in _KEYSET:
        _ = k


def s_wf614_clean(r):
    if r is None:
        return
    for k in sorted(_KEYSET):    # order-insensitive consumer: fine
        _ = k


def k_suppressed(t):
    # the cast below is provably concrete in this fixture's contract
    v = float(t["v"])  # wfverify: ok (seeded fixture for the suppression test)
    return {"k": t["k"], "v": v}


def k_suppressed_no_reason(t):
    v = float(t["v"])  # wfverify: ok
    return {"k": t["k"], "v": v}


CALLABLE_CASES = [
    ("WF801", k_wf801, True, False),
    ("WF801", k_wf801_np, True, False),
    ("WF802", k_wf802, True, False),
    ("WF803", k_wf803, True, False),
    ("WF804", k_wf804, True, False),
    ("WF811", k_wf811, True, False),
    ("WF812", k_wf812, True, False),
    ("WF812", k_wf812_mask, True, False),
    ("WF612", k_wf612, True, True),
    ("WF611", s_wf611, False, True),
    ("WF613", s_wf613_id, False, True),
    ("WF613", s_wf613_hash, False, True),
    ("WF614", s_wf614, False, True),
]

CLEAN_TWINS = [
    (k_clean, True, True),
    (k_wf802_clean, True, False),
    (k_wf803_local, True, False),
    (k_wf811_clean, True, False),
    (k_wf812_clean, True, False),
    (s_wf611_clean, False, True),
    (s_wf614_clean, False, True),
]


@pytest.mark.parametrize("want,fn,traced,durable", CALLABLE_CASES,
                         ids=[f"{c[0]}-{c[1].__name__}"
                              for c in CALLABLE_CASES])
def test_seeded_violation_caught(want, fn, traced, durable):
    findings = tc.verify_callable(fn, traced=traced, durable=durable)
    assert want in codes(findings), codes(findings)
    hit = next(f for f in findings if f.code == want)
    # anchored to this file, inside the fixture's body
    assert os.path.basename(hit.path) == THIS
    lo = fn.__code__.co_firstlineno
    assert lo <= hit.lineno <= lo + 10
    assert want in CODES     # every emitted code is in the table


@pytest.mark.parametrize("fn,traced,durable", CLEAN_TWINS,
                         ids=[c[0].__name__ for c in CLEAN_TWINS])
def test_clean_twin_no_diagnostics(fn, traced, durable):
    assert tc.verify_callable(fn, traced=traced, durable=durable) == []


def test_determinism_family_gated_on_durability():
    # the same wall-clock kernel is a WF811 bake hazard without
    # durability and a WF612 replay hazard with it — never both at once
    with_d = codes(tc.verify_callable(k_wf612, traced=True, durable=True))
    without = codes(tc.verify_callable(k_wf612, traced=True,
                                       durable=False))
    assert "WF612" in with_d and "WF811" not in with_d
    assert "WF811" in without and "WF612" not in without


# ---------------------------------------------------------------------------
# donation (WF821)
# ---------------------------------------------------------------------------

class LeakyMapTPU(wf.MapTPU):
    """Seeded WF821: donates the payload then reads it after dispatch."""

    def __init__(self, fn, **kw):
        super().__init__(fn, **kw)
        self._jit_donating = wf_jit(lambda p, v: (p, v),
                                    op_name="leaky_fixture",
                                    donate_argnums=(0,))

    def _step(self, batch):
        payload, valid = self._jit_donating(batch.payload, batch.valid)
        leak = batch.payload     # the donated buffer is dead here
        return leak and None


class CleanDonatingMapTPU(wf.MapTPU):
    """Clean twin: every read happens before the donating dispatch, and
    the donated expression is immediately rebound."""

    def __init__(self, fn, **kw):
        super().__init__(fn, **kw)
        self._jit_donating = wf_jit(lambda p, v: (p, v),
                                    op_name="clean_fixture",
                                    donate_argnums=(0,))

    def _step(self, batch):
        wm = batch.watermark
        batch.payload, valid = self._jit_donating(batch.payload,
                                                  batch.valid)
        return wm and None


def test_wf821_donated_read_after_dispatch():
    op = LeakyMapTPU(k_clean, name="leak")
    findings = tc.verify_dispatcher(LeakyMapTPU._step, op)
    assert codes(findings) == ["WF821"]
    assert "batch.payload" in findings[0].message


def test_wf821_clean_twin_and_shipped_steps():
    op = CleanDonatingMapTPU(k_clean, name="ok")
    assert tc.verify_dispatcher(CleanDonatingMapTPU._step, op) == []
    # the framework's own donating dispatchers must stay clean: FFAT and
    # stateful steps donate their state ring (donate_argnums=(0,)) and
    # rebind it from the program's outputs on the same statement
    from windflow_tpu.ops.tpu import ReduceTPU
    from windflow_tpu.ops.tpu_stateful import StatefulMapTPU
    from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU
    assert tc._class_donation_map(FfatWindowsTPU).get("_jit_step") == {0}
    assert tc._class_donation_map(ReduceTPU).get("_get_step") == {1, 2, 3}
    assert tc._class_donation_map(StatefulMapTPU).get("_get_step") == {0}


def test_wf821_branch_path_union():
    class BranchLeaky(wf.MapTPU):
        def __init__(self, fn, **kw):
            super().__init__(fn, **kw)
            self._jit_donating = wf_jit(lambda p: p, op_name="br_fix",
                                        donate_argnums=(0,))

        def _step(self, batch):
            if batch.watermark:
                out = self._jit_donating(batch.payload)
            else:
                out = None
            return out, batch.payload   # read on the donated path

    op = BranchLeaky(k_clean, name="br")
    assert "WF821" in codes(tc.verify_dispatcher(BranchLeaky._step, op))


# ---------------------------------------------------------------------------
# suppression contract
# ---------------------------------------------------------------------------

def test_suppression_with_reason_honored():
    assert tc.verify_callable(k_suppressed, traced=True,
                              durable=False) != []  # raw findings stay
    g = _graph(k_suppressed)
    rep = tc.verify_graph(g)
    assert rep.diagnostics == []
    assert [d.code for d in rep.suppressed] == ["WF801"]


def test_suppression_without_reason_rejected():
    g = _graph(k_suppressed_no_reason)
    rep = tc.verify_graph(g)
    assert [d.code for d in rep.diagnostics] == ["WF801"]
    assert "without a (reason)" in rep.diagnostics[0].message
    assert rep.suppressed == []


# ---------------------------------------------------------------------------
# graph-level + preflight integration
# ---------------------------------------------------------------------------

def _graph(kfn=k_clean, sink_fn=None, durability="", win=None):
    def gen():
        return iter({"k": i % 2, "v": float(i)} for i in range(8))

    cfg = dataclasses.replace(wf.default_config)
    if durability:
        cfg.durability = durability
    src = (wf.Source_Builder(gen).withOutputBatchSize(8)
           .withRecordSpec({"k": np.int32(0), "v": np.float32(0.0)})
           .build())
    g = wf.PipeGraph("tcheck", config=cfg)
    pipe = g.add_source(src)
    pipe.add(wf.MapTPU_Builder(kfn).withName("m").build())
    if win is not None:
        pipe.add(wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                            lambda a, b: a + b)
                 .withCBWindows(*win).withKeyBy(lambda t: t["k"])
                 .withMaxKeys(2).withName("w").build())
    pipe.add_sink(wf.Sink_Builder(sink_fn or (lambda r: None))
                  .withName("s").build())
    return g


def test_verify_graph_names_operator_and_location():
    rep = tc.verify_graph(_graph(k_wf801))
    hits = [d for d in rep.diagnostics if d.code == "WF801"]
    assert hits and hits[0].node == "m"
    assert THIS in hits[0].location


def test_verify_graph_clean_repo_style_graph():
    rep = tc.verify_graph(_graph(k_clean, win=(4, 2)))
    assert rep.diagnostics == [] and rep.checked > 4


def test_check_surfaces_wf8xx_alongside_existing_codes():
    # slide > len (WF202, warning) + host-materializing kernel (WF801,
    # error): one check() reports both families in the same table
    g = _graph(k_wf801, win=(4, 9))
    got = [d.code for d in g.check()]
    assert "WF202" in got and "WF801" in got
    # the eval-shape pass independently fails the same kernel (WF101):
    # the static twin fires WITHOUT tracing, same report
    assert "WF101" in got
    with pytest.warns(Warning):      # WF202 downgrades to a warning
        with pytest.raises(PreflightError) as ei:
            g.start()
    assert "WF801" in str(ei.value)


def test_check_durability_sink_determinism():
    g = _graph(k_clean, sink_fn=s_wf611, durability="/tmp/nonexistent_ck")
    got = [d.code for d in g.check()]
    assert "WF611" in got
    # warning severity: a preflight="error" start() would still run it


def test_preflight_section_reports_tracecheck():
    g = _graph(k_clean)
    g.check()
    assert g._tracecheck_report is not None
    assert g._tracecheck_report.checked > 0


# ---------------------------------------------------------------------------
# CLI round trip
# ---------------------------------------------------------------------------

APP_SRC = '''
import numpy as np
import windflow_tpu as wf

def bad_kernel(t):
    return {"k": t["k"], "v": float(t["v"])}

def make_graph():
    src = (wf.Source_Builder(lambda: iter(()))
           .withOutputBatchSize(8)
           .withRecordSpec({"k": np.int32(0), "v": np.float32(0.0)})
           .build())
    g = wf.PipeGraph("cliapp")
    pipe = g.add_source(src)
    pipe.add(wf.MapTPU_Builder(bad_kernel).withName("m").build())
    pipe.add_sink(wf.Sink_Builder(lambda r: None).build())
    return g
'''


def test_cli_json_round_trip(tmp_path):
    (tmp_path / "cliapp.py").write_text(APP_SRC)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=f"{tmp_path}{os.pathsep}{REPO}")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_verify.py"),
         "cliapp:make_graph", "--json"],
        capture_output=True, text=True, env=env, timeout=180)
    assert out.returncode == 1, out.stderr    # WF801 is error severity
    payload = json.loads(out.stdout)
    rep = payload["cliapp:make_graph"]
    assert rep["graph"] == "cliapp" and rep["errors"] >= 1
    assert any(d["code"] == "WF801" for d in rep["diagnostics"])
    assert all(d["code"] in CODES for d in rep["diagnostics"])
    # --strict over the shipped bench entrypoint stays clean (the CI
    # stage's contract); reuse THIS interpreter via direct main() call
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "wf_verify", os.path.join(REPO, "tools", "wf_verify.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["tools.verify_targets:bench_e2e", "--strict"]) == 0


# ---------------------------------------------------------------------------
# static/dynamic cross-validation (the wallclock chaos family)
# ---------------------------------------------------------------------------

def test_wallclock_family_caught_static(tmp_path):
    from windflow_tpu.durability import chaos
    cell = chaos.make_cell("wallclock", str(tmp_path / "ck"), n=64)
    rep = tc.verify_graph(cell["factory"]())
    assert "WF612" in [d.code for d in rep.diagnostics]
    # warning severity: the graph still RUNS (the dynamic half of the
    # cross-validation needs it to), the finding just names the hazard
    assert all(d.severity == "warning" for d in rep.diagnostics
               if d.code == "WF612")
    assert "wallclock" in chaos.DETERMINISM_FAMILIES
    assert "wallclock" not in chaos.FAMILIES     # not in the soak matrix


def test_wallclock_family_expected_fail_dynamic_caught_static(tmp_path):
    """The cross-validation cell: wfverify flags WF612 on the SAME graph
    whose chaos kill->restore->diff fails dynamically.  Expected-fail-
    dynamic (the replay diverges because the re-trace bakes a new
    clock), caught-static (WF612 named it before any batch ran)."""
    import warnings

    from windflow_tpu.durability import chaos
    base = chaos.make_cell("wallclock", str(tmp_path / "ck_a"), n=4096)
    chal = chaos.make_cell("wallclock", str(tmp_path / "ck_b"), n=4096)
    rep = tc.verify_graph(base["factory"]())
    assert "WF612" in [d.code for d in rep.diagnostics]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        verdict = chaos.run_ab(base["factory"], chal["factory"],
                               chaos.default_kill("wallclock",
                                                  "mid_epoch"),
                               base["read"], chal["read"])
    assert verdict["diff"] is not None, \
        "the seeded determinism violation stopped violating"


# ---------------------------------------------------------------------------
# caching / cost
# ---------------------------------------------------------------------------

def test_verify_cache_by_code_object():
    f1 = tc.verify_callable(k_clean, traced=True, durable=False)
    f2 = tc.verify_callable(k_clean, traced=True, durable=False)
    assert f1 is f2     # cached by code object


def test_framework_bodies_and_dispatchers_clean():
    # the shipped chained/fused wf_jit bodies and every _step dispatcher
    # reachable from a representative graph verify clean — the classic
    # static-analysis payoff the CI stage (ci/run_tests.sh) pins over
    # the bench/chaos entrypoints
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "verify_targets", os.path.join(REPO, "tools",
                                       "verify_targets.py"))
    vt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(vt)
    rep = tc.verify_graph(vt.bench_e2e())
    assert rep.diagnostics == []
