"""Whole-chain fusion executor contracts (windflow_tpu/fusion,
docs/PERF.md round 10): record-for-record equivalence of fused vs.
unfused execution across the graph families (window tails CB/TB, keyed
reduce, dense-key stateful, all-stateless, split/merge boundaries),
the exact one-jitted-dispatch-per-batch accounting through the sweep
ledger, zero donation misses on the bench-shaped graph, keys-lane
forwarding through chains into KEYBY consumers, and the
``WF_TPU_FUSE`` kill-switch off-path."""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import default_config
from windflow_tpu.monitoring.jit_registry import default_registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CAP = 64
N = CAP * 6
N_KEYS = 8


def _cfg(fuse: bool, **kw):
    return dataclasses.replace(default_config, whole_chain_fusion=fuse,
                               **kw)


def _records_sink(got):
    def sink(r, ctx=None):
        if r is None:
            return
        got.append(tuple(sorted(r.items())) if isinstance(r, dict)
                   else float(r))
    return wf.Sink_Builder(sink).withName("snk").build()


def _source(event_time=False, n=N, cap=CAP):
    if event_time:
        return (wf.Source_Builder(
            lambda: iter({"key": np.int32(i % N_KEYS),
                          "v": np.float32(i),
                          "ts": np.int64(i * 1000)} for i in range(n)))
            .withName("src").withTimestampExtractor(lambda t: t["ts"])
            .withOutputBatchSize(cap).build())
    return (wf.Source_Builder(
        lambda: iter({"key": np.int32(i % N_KEYS), "v": np.float32(i)}
                     for i in range(n)))
        .withName("src").withOutputBatchSize(cap)
        .withRecordSpec({"key": np.int32(0), "v": np.float32(0.0)})
        .build())


def _map_filter():
    ma = (wf.MapTPU_Builder(lambda t: {"key": t["key"], "v": t["v"] * 2.0})
          .withName("ma").build())
    fb = (wf.FilterTPU_Builder(lambda t: (t["key"] & 1) == 0)
          .withName("fb").build())
    return ma, fb


def _tail(kind):
    if kind == "cb_window":
        return (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                           lambda a, b: a + b)
                .withCBWindows(8, 4).withKeyBy(lambda t: t["key"])
                .withMaxKeys(N_KEYS).withName("win").build())
    if kind == "tb_window":
        return (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                           lambda a, b: a + b)
                .withTBWindows(16_000, 8_000)
                .withKeyBy(lambda t: t["key"])
                .withMaxKeys(N_KEYS).withName("win").build())
    if kind == "reduce":
        return (wf.ReduceTPU_Builder(
            lambda a, b: {"key": a["key"], "v": a["v"] + b["v"]})
            .withKeyBy(lambda t: t["key"]).withName("red").build())
    if kind == "stateful_dense":
        return (wf.MapTPU_Builder(
            lambda t, s: ({"key": t["key"], "v": t["v"] + s}, s + 1.0))
            .withInitialState(np.float32(0.0))
            .withKeyBy(lambda t: t["key"]).withNumKeySlots(N_KEYS * 2)
            .withDenseKeys().withName("sm").build())
    if kind == "stateful_intern":
        # host-interning tail: the executor must fuse ONLY the stateless
        # prefix (the intern's distinct-key D2H cannot run mid-program)
        return (wf.MapTPU_Builder(
            lambda t, s: ({"key": t["key"], "v": t["v"] + s}, s + 1.0))
            .withInitialState(np.float32(0.0))
            .withKeyBy(lambda t: t["key"]).withNumKeySlots(N_KEYS * 2)
            .withName("sm").build())
    assert kind == "stateless"
    return None


def _run_family(kind, fuse):
    got = []
    event = kind == "tb_window"
    tl = _tail(kind)
    ma, fb = _map_filter()
    g = wf.PipeGraph(f"fuse_{kind}", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT if event
                     else wf.TimePolicy.INGRESS,
                     config=_cfg(fuse))
    p = g.add_source(_source(event_time=event))
    p.add(ma)
    p.add(fb)
    if tl is not None:
        p.add(tl)
    p.add_sink(_records_sink(got))
    g.run()
    return sorted(got), g


# ---------------------------------------------------------------------------
# record-for-record fused vs unfused A/B (the acceptance contract)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind", ["cb_window", "tb_window", "reduce",
                                  "stateful_dense", "stateful_intern",
                                  "stateless"])
def test_fused_equals_unfused(kind):
    unfused, _ = _run_family(kind, fuse=False)
    fused, g = _run_family(kind, fuse=True)
    assert fused == unfused
    assert len(fused) > 0
    segs = [s["name"] for s in g._fused_segments]
    if kind == "stateless":
        assert segs == ["ma|fb"]
    elif kind == "stateful_intern":
        assert segs == ["ma|fb"]        # prefix only: intern tail excluded
    else:
        assert len(segs) == 1 and segs[0].startswith("ma|fb|")


def test_fused_equals_unfused_split_graph():
    """Fusion must stop at split boundaries yet still fuse the runs
    INSIDE each branch; both configurations agree record for record."""
    def run(fuse):
        got = [[], []]

        def mk(i):
            def sink(r, ctx=None):
                if r is None:
                    return
                got[i].append(tuple(sorted(r.items())))
            return wf.Sink_Builder(sink).build()

        g = wf.PipeGraph("fuse_split", wf.ExecutionMode.DEFAULT,
                         wf.TimePolicy.INGRESS, config=_cfg(fuse))
        p = g.add_source(_source())
        p.add(wf.MapTPU_Builder(
            lambda t: {"key": t["key"], "v": t["v"] + 1.0})
            .withName("pre").build())
        p.split(lambda t: t["key"] % 2, 2)
        for b in range(2):
            br = p.select(b)
            br.add(wf.MapTPU_Builder(
                lambda t: {"key": t["key"], "v": t["v"] * 3.0})
                .withName(f"m{b}").build())
            br.add(wf.FilterTPU_Builder(lambda t: (t["key"] & 3) != 3)
                   .withName(f"f{b}").build())
            br.add_sink(mk(b))
        g.run()
        return [sorted(x) for x in got], g

    a, _ = run(False)
    b, g = run(True)
    assert a == b
    # one fused segment per branch; the pre-split op stays unfused
    assert sorted(s["name"] for s in g._fused_segments) \
        == ["m0|f0", "m1|f1"]


def test_fused_equals_unfused_merged_sources():
    """A merge feeding the chain head: the merge edge redirects into the
    fused host like any op edge; results agree with the unfused run."""
    def run(fuse):
        got = []
        g = wf.PipeGraph("fuse_merge", wf.ExecutionMode.DEFAULT,
                         wf.TimePolicy.INGRESS, config=_cfg(fuse))
        p1 = g.add_source(_source(n=N // 2))
        src2 = (wf.Source_Builder(
            lambda: iter({"key": np.int32(i % N_KEYS),
                          "v": np.float32(1000 + i)}
                         for i in range(N // 2)))
            .withName("src2").withOutputBatchSize(CAP).build())
        p2 = g.add_source(src2)
        merged = p1.merge(p2)
        ma, fb = _map_filter()
        merged.add(ma)
        merged.add(fb)
        merged.add(_tail("cb_window"))
        merged.add_sink(_records_sink(got))
        g.run()
        return sorted(got), g

    a, _ = run(False)
    b, g = run(True)
    assert a == b and len(a) > 0
    assert [s["name"] for s in g._fused_segments] == ["ma|fb|win"]


# ---------------------------------------------------------------------------
# dispatch accounting: a fused N-op chain = ONE jitted dispatch per batch
# ---------------------------------------------------------------------------

def test_fused_chain_exactly_one_dispatch_per_batch():
    """The acceptance contract: the fused 3-op chain's program pays
    exactly one jitted dispatch per data batch (registry counter — the
    CB EOS flush is a separate one-shot program), the member hops pay
    zero, and the ledger's sweep total collapses to 1/batch."""
    default_registry().reset()
    _, g = _run_family("cb_window", fuse=True)
    n_batches = N // CAP
    entry = default_registry().snapshot()["ma|fb|win"]
    assert entry["dispatches"] == n_batches
    sweep = g.stats()["Sweep"]
    for m in ("ma", "fb"):
        hop = sweep["per_hop"][m]
        assert hop["dispatches"] == 0
        assert hop["fused_into"] == "ma|fb|win"
    host = sweep["per_hop"]["win"]
    assert host["fused_program"] == "ma|fb|win"
    assert host["fused_members"] == ["ma", "fb", "win"]
    assert host["dispatches_per_batch"] == 1.0
    assert sweep["totals"]["dispatches_per_batch"] == 1.0
    fus = sweep["fusion"]
    assert fus["enabled"] is True
    assert fus["fused_chains"] == ["ma|fb|win"]
    assert fus["dispatches_saved_per_batch"] == 2.0
    assert fus["bytes_saved_per_batch"] > 0
    json.dumps(sweep)


def test_fused_stateless_chain_dispatch_attribution():
    """An all-stateless fused segment's program lives on the host op's
    FusedStatelessExec — the ledger must still attribute its dispatches
    to the host hop (the _op_wrappers fused-exec arm)."""
    default_registry().reset()
    _, g = _run_family("stateless", fuse=True)
    sweep = g.stats()["Sweep"]
    assert sweep["per_hop"]["ma"]["dispatches"] == 0
    host = sweep["per_hop"]["fb"]
    assert host["dispatches"] == N // CAP
    assert host["dispatches_per_batch"] == 1.0
    assert sweep["totals"]["dispatches_per_batch"] == 1.0


def test_kill_switch_restores_per_hop_dispatches():
    """WF_TPU_FUSE=0 / Config.whole_chain_fusion=False: every hop pays
    its own dispatch again and no segments are installed."""
    _, g = _run_family("cb_window", fuse=False)
    assert g._fused_segments == []
    sweep = g.stats()["Sweep"]
    for m in ("ma", "fb", "win"):
        assert sweep["per_hop"][m]["dispatches_per_batch"] == 1.0
        assert "fused_into" not in sweep["per_hop"][m]
    assert sweep["totals"]["dispatches_per_batch"] == 3.0
    assert sweep["fusion"]["enabled"] is False


# ---------------------------------------------------------------------------
# donation: zero misses on the bench-shaped graph (fused AND unfused)
# ---------------------------------------------------------------------------

def _bench_shaped_graph(fuse):
    src = _source()
    m = (wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "v": t["v"] * 1.5 + 1.0})
        .withName("map_tpu").build())
    f = (wf.FilterTPU_Builder(lambda t: (t["key"] & 7) != 7)
         .withName("filter_tpu").build())
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"], lambda a, b: a + b)
         .withCBWindows(16, 8).withKeyBy(lambda t: t["key"])
         .withMaxKeys(N_KEYS).withName("win").build())
    snk = wf.Sink_Builder(lambda r: None).withName("snk").build()
    g = wf.PipeGraph("bench_shape", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.INGRESS, config=_cfg(fuse))
    pipe = g.add_source(src)
    pipe.add(m)
    pipe.chain(f)       # the bench graph's chained pair
    pipe.add(w).add_sink(snk)
    return g


@pytest.mark.parametrize("fuse", [False, True])
def test_bench_graph_zero_donation_misses(fuse):
    """The donation satellite's acceptance: with the chained-pair step
    donating its (provably unshared) staged inputs and the FFAT state
    already donated, the bench-shaped graph shows ZERO donation-miss
    bytes — fused and unfused alike."""
    g = _bench_shaped_graph(fuse)
    g.run()
    sweep = g.stats()["Sweep"]
    assert sweep["totals"]["donation_miss_bytes_per_batch"] == 0.0
    for name, hop in sweep["per_hop"].items():
        assert "donation_miss" not in hop, (name, hop)


def test_staging_pool_survives_donated_gates():
    """Input donation deletes the staged valid/payload lanes; the pool's
    recycling gate must survive that — it rides the unpack program's
    PRIVATE scalar output no consumer can donate (batch.stage_packed),
    so acquire never syncs on a deleted array."""
    g = _bench_shaped_graph(False)
    g.run()     # chained pair donates staged payload+valid every batch
    from windflow_tpu import staging
    st = staging.default_pool().stats()
    assert st["releases"] > 0       # buffers really were recycled


# ---------------------------------------------------------------------------
# keys lane through chains (the ChainedTPU satellite)
# ---------------------------------------------------------------------------

def _keyed_consumer_graph(chained, par=1, fuse=False):
    got = []
    src = _source()
    ma, fb = _map_filter()
    sm = (wf.MapTPU_Builder(
        lambda t, s: ({"key": t["key"], "v": t["v"] + s}, s + 1.0))
        .withInitialState(np.float32(0.0))
        .withKeyBy(lambda t: t["key"]).withNumKeySlots(N_KEYS * 2)
        .withParallelism(par).withName("sm").build())
    g = wf.PipeGraph("keys_lane", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.INGRESS, config=_cfg(fuse))
    p = g.add_source(src)
    p.add(ma)
    (p.chain if chained else p.add)(fb)
    p.add(sm).add_sink(_records_sink(got))
    g.run()
    return sorted(got)


def test_keyby_after_fused_chain_preserves_keys_lane():
    """Regression for the dropped keys lane: a ChainedTPU feeding a
    KEYBY consumer now extracts the consumer's keys inside its own
    program (on the chain's OUTPUT records) and ships them on the keys
    lane — the consumer's standalone ``.key_extract`` program never
    compiles, and results match the unchained graph exactly."""
    default_registry().reset()
    chained = _keyed_consumer_graph(chained=True)
    snap = set(default_registry().snapshot())
    assert "sm.key_extract" not in snap
    default_registry().reset()
    unchained = _keyed_consumer_graph(chained=False)
    assert "sm.key_extract" in set(default_registry().snapshot())
    assert chained == unchained and len(chained) > 0


@pytest.mark.slow
def test_keyby_after_fused_chain_multi_replica_routing():
    """At parallelism 2 the keyby emitter consumes the chain-forwarded
    keys lane for placement: every key still lands on one replica and
    the results match the single-replica run.  Slow: two extra full
    graph runs buying a routing-consistency check the par-1 regression
    above already anchors."""
    base = _keyed_consumer_graph(chained=True, par=1)
    multi = _keyed_consumer_graph(chained=True, par=2)
    assert multi == base


# ---------------------------------------------------------------------------
# stats / observability contracts for fused members
# ---------------------------------------------------------------------------

def test_member_stats_attributed_from_fused_hop():
    _, g = _run_family("cb_window", fuse=True)
    stats = g.stats()
    ops = {o["Operator_name"]: o for o in stats["Operators"]}
    assert ops["ma"]["Fused_into"] == "ma|fb|win"
    assert ops["fb"]["Fused_into"] == "ma|fb|win"
    assert "Fused_into" not in ops["win"]
    host_inputs = sum(r["Inputs_received"]
                      for r in ops["win"]["Replicas"])
    assert host_inputs == N
    assert sum(r["Inputs_received"] for r in ops["ma"]["Replicas"]) == N
    # the report stays JSON-clean with fused segments installed
    json.dumps(stats, default=str)


def test_health_reads_fused_members_as_terminated():
    """Inert member replicas must read as cleanly terminated — never
    STALLED — under the watchdog."""
    _, g = _run_family("cb_window", fuse=True)
    health = g.stats()["Health"]
    if health.get("enabled", True):
        for name in ("ma", "fb"):
            v = health["verdicts"][name]
            assert v["state"] == "OK", v


# ---------------------------------------------------------------------------
# advisor --verify (projected vs realized)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_advisor_verify_cli_roundtrip(tmp_path):
    """tools/wf_advisor.py --verify: a fusion-ON run's stats dump
    verifies against the module's plan — every executable chain
    realized one dispatch/batch, exit 0."""
    g = _bench_shaped_graph(True)
    g.run()
    dump = tmp_path / "stats.json"
    dump.write_text(json.dumps({"Sweep": g.stats()["Sweep"]},
                               default=str))
    app = tmp_path / "verify_app.py"
    app.write_text(
        "import numpy as np\n"
        "import windflow_tpu as wf\n\n"
        "def make_graph():\n"
        "    src = (wf.Source_Builder(lambda: iter(()))\n"
        "           .withOutputBatchSize(64).withName('src')\n"
        "           .withRecordSpec({'key': np.int32(0),\n"
        "                            'v': np.float32(0.0)}).build())\n"
        "    m = wf.MapTPU_Builder(\n"
        "        lambda t: {'key': t['key'], 'v': t['v'] * 1.5 + 1.0})\\\n"
        "        .withName('map_tpu').build()\n"
        "    f = wf.FilterTPU_Builder(\n"
        "        lambda t: (t['key'] & 7) != 7)\\\n"
        "        .withName('filter_tpu').build()\n"
        "    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t['v'],\n"
        "                                    lambda a, b: a + b)\n"
        "         .withCBWindows(16, 8).withKeyBy(lambda t: t['key'])\n"
        "         .withMaxKeys(8).withName('win').build())\n"
        "    snk = wf.Sink_Builder(lambda r: None).build()\n"
        "    g = wf.PipeGraph('bench_shape')\n"
        "    p = g.add_source(src)\n"
        "    p.add(m)\n"
        "    p.chain(f)\n"
        "    p.add(w).add_sink(snk)\n"
        "    return g\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=f"{tmp_path}{os.pathsep}{REPO}")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_advisor.py"),
         "verify_app", "--verify", str(dump), "--json"],
        capture_output=True, text=True, env=env, timeout=120)
    assert out.returncode == 0, (out.stdout, out.stderr)
    payload = json.loads(out.stdout)
    assert payload["chains"], payload
    realized = [c for c in payload["chains"] if c.get("realized")]
    assert realized, payload
    assert realized[0]["realized"]["dispatches_per_batch"] <= 1.05
