"""Declared-monoid combiners (withMonoidCombiner: sum | max | min).

The declaration routes count-based FFAT onto the scatter-combine /
flagless-fold fast paths and time-based FFAT onto the sort-free ring
placement — for max/min those paths are IDEMPOTENT, so results must be
bit-identical to the default flag-aware machinery (no float-reorder
tolerance needed, unlike "sum").

Values are strictly NEGATIVE floats throughout: any slot the kernels
fill with 0 instead of the monoid identity (-inf for max) would win a
max and corrupt a window, so these streams catch identity bugs that
non-negative data hides.  Reference anchor: the CUDA FFAT pays its
sort/tree machinery for every combiner alike
(``ffat_replica_gpu.hpp:751,917``); the declared-monoid bypass is
TPU-side design, not ported behavior.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.windows.ffat_kernels import (make_ffat_state,
                                               make_ffat_step,
                                               make_ffat_tb_state,
                                               make_ffat_tb_step)

CAP, K, WIN, SLIDE = 512, 8, 64, 16
Pn = math.gcd(WIN, SLIDE)
R, D = WIN // Pn, SLIDE // Pn


def _batches(n, rng, negative=True):
    out = []
    for i in range(n):
        vals = rng.random(CAP, dtype=np.float32)
        if negative:
            vals = -1.0 - vals          # all < -1: identity bugs surface
        out.append((
            {"k": jnp.asarray(rng.integers(0, K, CAP), jnp.int32),
             "v": jnp.asarray(vals)},
            jnp.asarray(np.arange(CAP) + i * CAP, jnp.int64),
            jnp.asarray(rng.random(CAP) > 0.15),     # invalid lanes too
        ))
    return out


def _run_cb(monoid, comb, batches, grouping="rank_scatter"):
    step = jax.jit(make_ffat_step(CAP, K, Pn, R, D, lambda x: x["v"], comb,
                                  lambda x: x["k"], monoid=monoid,
                                  grouping=grouping))
    st = make_ffat_state(jnp.zeros((), jnp.float32), K, R)
    fired = {}
    for payload, ts, valid in batches:
        st, out, ov, _ = step(st, payload, ts, valid)
        ovn = np.asarray(ov)
        keys = np.asarray(out["key"])[ovn]
        wids = np.asarray(out["wid"])[ovn]
        vals = np.asarray(out["value"])[ovn]
        for k_, w_, v_ in zip(keys, wids, vals):
            fired[(int(k_), int(w_))] = float(v_)
    return fired, st


@pytest.mark.parametrize("monoid,comb", [
    ("max", lambda a, b: jnp.maximum(a, b)),
    ("min", lambda a, b: jnp.minimum(a, b)),
])
def test_cb_monoid_scatter_path_bit_identical_to_default(monoid, comb):
    """Declared max/min (idempotent) on the CB scatter-combine path must
    equal the undeclared flag-aware path EXACTLY, windows and state."""
    rng = np.random.default_rng(11)
    batches = _batches(6, rng)
    got, st_m = _run_cb(monoid, comb, batches)
    want, st_d = _run_cb(None, comb, batches)
    assert got == want and len(got) > 0
    for a, b in zip(jax.tree.leaves(st_m), jax.tree.leaves(st_d)):
        if a.dtype == jnp.bool_ or jnp.issubdtype(a.dtype, jnp.integer):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cb_monoid_flagless_fold_under_argsort_grouping():
    """monoid + argsort grouping exercises the permutation path with the
    identity-filled flagless fold (no scatter-combine) — still exact."""
    rng = np.random.default_rng(12)
    batches = _batches(5, rng)
    got, _ = _run_cb("max", lambda a, b: jnp.maximum(a, b), batches,
                     grouping="argsort")
    want, _ = _run_cb(None, lambda a, b: jnp.maximum(a, b), batches,
                      grouping="argsort")
    assert got == want and len(got) > 0


def test_cb_declared_sum_still_matches_int_oracle():
    """The legacy sum declaration through the generalized plumbing:
    integer sums are exact, so declared == undeclared bitwise."""
    rng = np.random.default_rng(13)
    batches = []
    for i in range(5):
        payload = {"k": jnp.asarray(rng.integers(0, K, CAP), jnp.int32),
                   "v": jnp.asarray(rng.integers(-50, 50, CAP), jnp.int32)}
        batches.append((payload,
                        jnp.asarray(np.arange(CAP) + i * CAP, jnp.int64),
                        jnp.asarray(rng.random(CAP) > 0.1)))
    step_kw = dict(sum_like=True)    # legacy spelling must still work

    def run(**kw):
        step = jax.jit(make_ffat_step(
            CAP, K, Pn, R, D, lambda x: x["v"], lambda a, b: a + b,
            lambda x: x["k"], **kw))
        st = make_ffat_state(jnp.zeros((), jnp.int32), K, R)
        fired = {}
        for payload, ts, valid in batches:
            st, out, ov, _ = step(st, payload, ts, valid)
            m = np.asarray(ov)
            for k_, w_, v_ in zip(np.asarray(out["key"])[m],
                                  np.asarray(out["wid"])[m],
                                  np.asarray(out["value"])[m]):
                fired[(int(k_), int(w_))] = int(v_)
        return fired
    assert run(**step_kw) == run() and len(run()) > 0


def test_tb_monoid_scatter_placement_matches_default():
    """TB max through the sort-free scatter placement == the grouped
    default, against a python oracle."""
    stream = [{"key": i % 3, "value": -1.0 - ((i * 37) % 101) / 10.0,
               "ts": i * 1000} for i in range(240)]
    per_key = {}
    for t in stream:
        per_key.setdefault(t["key"], []).append((t["ts"], t["value"]))
    # oracle: per-key max over every [w*4000, w*4000+16000) window
    exp = {}
    for k_, pts in per_key.items():
        tmax = max(ts for ts, _ in pts)
        w = 0
        while w * 4000 <= tmax:
            vals = [v for ts, v in pts
                    if w * 4000 <= ts < w * 4000 + 16000]
            if vals:
                exp[(k_, w)] = max(vals)
            w += 1
    # windows whose span starts after the last tuple never fire; also the
    # trailing partials fire at EOS — both covered by comparing sets
    for declare in (False, True):
        got = {}
        src = (wf.Source_Builder(lambda: iter(stream))
               .withTimestampExtractor(lambda t: t["ts"])
               .withOutputBatchSize(31).build())
        b = (wf.Ffat_WindowsTPU_Builder(
                lambda t: t["value"], lambda a, b: jnp.maximum(a, b))
             .withKeyBy(lambda t: t["key"]).withMaxKeys(3)
             .withTBWindows(16_000, 4_000))
        if declare:
            b = b.withMonoidCombiner("max")
        snk = wf.Sink_Builder(
            lambda r: got.__setitem__((r["key"], r["wid"]), r["value"])
            if r is not None else None).build()
        g = wf.PipeGraph("ffat_tb_max", wf.ExecutionMode.DEFAULT,
                         wf.TimePolicy.EVENT)
        g.add_source(src).add(b.build()).add_sink(snk)
        g.run()
        assert got == exp, (declare, len(got), len(exp))


def test_whole_graph_cb_sliding_min_matches_oracle():
    """Builder plumbing end-to-end: withMonoidCombiner("min") on CB
    windows through PipeGraph.run() against a python sliding-min oracle."""
    N, NK, W, S = 4000, 5, 32, 8
    vals = [-(1.0 + ((i * 13) % 97)) for i in range(N)]

    def gen():
        for i in range(N):
            yield {"key": i % NK, "v": vals[i]}

    per_key = {}
    for i in range(N):
        per_key.setdefault(i % NK, []).append(vals[i])
    exp = {}
    for k_, vs in per_key.items():
        wid = 0
        start = 0
        while start + W <= len(vs):
            exp[(k_, wid)] = min(vs[start:start + W])
            wid += 1
            start += S
    got = {}
    src = wf.Source_Builder(gen).withOutputBatchSize(256).build()
    op = (wf.Ffat_WindowsTPU_Builder(
            lambda t: t["v"], lambda a, b: jnp.minimum(a, b))
          .withCBWindows(W, S).withKeyBy(lambda t: t["key"])
          .withMaxKeys(NK).withMonoidCombiner("min").build())
    snk = wf.Sink_Builder(
        lambda r: got.__setitem__((r["key"], r["wid"]), r["value"])
        if r is not None else None).build()
    g = wf.PipeGraph("ffat_cb_min", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(op).add_sink(snk)
    g.run()
    for key, v in exp.items():
        assert key in got and abs(got[key] - v) < 1e-6, key
    # EOS flushes trailing partial windows beyond the oracle's full ones
    assert len(got) >= len(exp)


def test_unknown_monoid_rejected():
    with pytest.raises(wf.WindFlowError, match="monoid"):
        (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                    lambda a, b: a * b)
         .withCBWindows(32, 8).withMaxKeys(4)
         .withMonoidCombiner("product").build())
    with pytest.raises(wf.WindFlowError, match="monoid"):
        (wf.ReduceTPU_Builder(lambda a, b: a)
         .withKeyBy(lambda t: t["key"]).withMaxKeys(4)
         .withMonoidCombiner("product").build())
    with pytest.raises(ValueError, match="monoid"):
        make_ffat_step(64, 4, 8, 4, 1, lambda x: x["v"],
                       lambda a, b: a + b, lambda x: x["k"],
                       monoid="product")
    with pytest.raises(ValueError, match="monoid"):
        make_ffat_tb_step(64, 4, 1000, 4, 1, 64, lambda x: x["v"],
                          lambda a, b: a + b, lambda x: x["k"],
                          monoid="product")


def test_tb_kernel_monoid_min_negative_and_positive():
    """Direct TB kernel check with mixed-sign values and a min monoid
    (identity +inf): declared == undeclared exactly."""
    B, KK, P_usec, RR, DD, NP = 128, 4, 1000, 4, 1, 64
    rng = np.random.default_rng(14)

    def run(monoid):
        step = jax.jit(make_ffat_tb_step(
            B, KK, P_usec, RR, DD, NP, lambda x: x["v"],
            lambda a, b: jnp.minimum(a, b), lambda x: x["k"],
            monoid=monoid))
        st = make_ffat_tb_state(jnp.zeros((), jnp.float32), KK, NP)
        fired = {}
        for i in range(4):
            payload = {"k": jnp.asarray(rng.integers(0, KK, B), jnp.int32),
                       "v": jnp.asarray(
                           rng.standard_normal(B).astype(np.float32))}
            ts = jnp.asarray(np.arange(B) * 250 + i * B * 250, jnp.int64)
            valid = jnp.asarray(rng.random(B) > 0.2)
            wm = jnp.asarray((i * B * 250) // P_usec, jnp.int64)
            st, out, f, _, _ = step(st, payload, ts, valid, wm)
            m = np.asarray(f)
            for k_, w_, v_ in zip(np.asarray(out["key"])[m],
                                  np.asarray(out["wid"])[m],
                                  np.asarray(out["value"])[m]):
                fired[(int(k_), int(w_))] = float(v_)
        return fired
    rng = np.random.default_rng(14)
    a = run("min")
    rng = np.random.default_rng(14)
    b = run(None)
    assert a == b and len(a) > 0


@pytest.mark.parametrize("horizon,lateness,expect_drops", [
    (12, 12_000, False),  # allowance covers the disorder horizon
    (30, 2_000, True),    # disorder beyond lateness + window span: drops
])
def test_tb_monoid_with_lateness_and_disorder_matches_default(
        horizon, lateness, expect_drops):
    """Declared-max TB placement under an out-of-order stream WITH a
    lateness allowance: the sort-free scatter path must agree with the
    grouped default exactly — late-but-allowed tuples land in already-open
    panes via scatter-combine, and too-late drops must be counted the
    same on both paths."""
    rnd = __import__("random").Random(40)
    stream = [{"key": i % 3, "value": -1.0 - ((i * 53) % 89) / 9.0,
               "ts": i * 1000} for i in range(300)]
    # shuffle within a fixed disorder horizon; drops require the
    # disorder to exceed lateness + the 20_000 us window span (panes
    # stay in the ring while any window over them is open)
    for i in range(0, 300 - horizon, horizon):
        seg = stream[i:i + horizon]
        rnd.shuffle(seg)
        stream[i:i + horizon] = seg

    def run(declare):
        got = {}
        drops = {}
        src = (wf.Source_Builder(lambda: iter(stream))
               .withTimestampExtractor(lambda t: t["ts"])
               .withOutputBatchSize(23).build())
        b = (wf.Ffat_WindowsTPU_Builder(
                lambda t: t["value"], lambda a, b: jnp.maximum(a, b))
             .withKeyBy(lambda t: t["key"]).withMaxKeys(3)
             .withTBWindows(20_000, 5_000).withLateness(lateness))
        if declare:
            b = b.withMonoidCombiner("max")
        op = b.build()
        snk = wf.Sink_Builder(
            lambda r: got.__setitem__((r["key"], r["wid"]), r["value"])
            if r is not None else None).build()
        g = wf.PipeGraph("ffat_tb_max_late", wf.ExecutionMode.DEFAULT,
                         wf.TimePolicy.EVENT)
        g.add_source(src).add(op).add_sink(snk)
        g.run()
        drops["late"] = op.dump_stats()["Late_tuples_dropped"]
        return got, drops

    got_m, d_m = run(True)
    got_d, d_d = run(False)
    assert got_m == got_d and len(got_m) > 0
    assert d_m == d_d
    if expect_drops:
        assert d_m["late"] > 0   # the drop path itself was exercised


def _run_reduce_graph(stream, declare, max_keys=None):
    # key_compaction OFF: this file pins the LEGACY declared-dense
    # contract (out-of-range keys dropped + warned) that only exists
    # under the WF_TPU_KEY_COMPACTION=0 kill switch since PR 11 —
    # the default-on reroute behavior is pinned by
    # tests/test_key_compaction.py
    import dataclasses

    from windflow_tpu.basic import default_config
    got = []
    src = (wf.Source_Builder(lambda: iter(stream))
           .withOutputBatchSize(64).build())
    b = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": jnp.maximum(a["key"], b["key"]),
                          "v": jnp.maximum(a["v"], b["v"])})
         .withKeyBy(lambda t: t["key"]))
    if max_keys is not None:
        b = b.withMaxKeys(max_keys)
    if declare:
        b = b.withMonoidCombiner("max")
    op = b.build()
    snk = wf.Sink_Builder(
        lambda r: got.append((int(r["key"]), float(r["v"])))
        if r is not None else None).build()
    g = wf.PipeGraph("reduce_dense", wf.ExecutionMode.DEFAULT,
                     config=dataclasses.replace(default_config,
                                                key_compaction=False))
    g.add_source(src).add(op).add_sink(snk)
    g.run()
    return got, op


def test_single_chip_dense_reduce_matches_sorted_path():
    """withMaxKeys + withMonoidCombiner on ONE chip: the sort-free dense
    scatter-combine table must emit exactly the records of the sorted
    segmented reduce (same per-batch distinct keys, ascending order, same
    values) — negative values so an identity bug wins a max."""
    stream = [{"key": i % 7, "v": -2.0 - ((i * 29) % 83) / 7.0}
              for i in range(512)]
    dense, op_d = _run_reduce_graph(stream, declare=True, max_keys=7)
    sorted_, _ = _run_reduce_graph(stream, declare=False)
    assert dense == sorted_ and len(dense) > 0
    assert op_d.dump_stats().get("Out_of_range_keys_dropped", 0) == 0


def test_single_chip_dense_reduce_drops_and_counts_out_of_range():
    """Keys outside [0, max_keys) cannot live in the dense table: they are
    dropped and surface in Out_of_range_keys_dropped (the documented
    withMaxKeys key-space contract), while the undeclared sorted path
    keeps them."""
    stream = [{"key": i % 10, "v": -1.0 - float(i % 13)}
              for i in range(320)]
    dense, op_d = _run_reduce_graph(stream, declare=True, max_keys=6)
    sorted_, _ = _run_reduce_graph(stream, declare=False)
    n_out_of_range = sum(1 for t in stream if t["key"] >= 6)
    assert op_d.dump_stats()["Out_of_range_keys_dropped"] == n_out_of_range
    assert sorted(set(k for k, _ in dense)) == list(range(6))
    # in-range records agree with the sorted path's in-range subset
    assert dense == [(k, v) for k, v in sorted_ if k < 6]


def test_single_chip_dense_reduce_non_keyed_single_record():
    """Non-keyed declared reduce: the dense path must emit ONE record per
    batch (K=1 global segment, the mesh contract) — not a max_keys-lane
    batch with one valid row."""
    stream = [{"v": -3.0 - float(i % 11)} for i in range(256)]
    got = []
    src = (wf.Source_Builder(lambda: iter(stream))
           .withOutputBatchSize(64).build())
    op = (wf.ReduceTPU_Builder(
            lambda a, b: {"v": jnp.maximum(a["v"], b["v"])})
          .withMaxKeys(4096).withMonoidCombiner("max").build())
    snk = wf.Sink_Builder(
        lambda r: got.append(float(r["v"])) if r is not None else None) \
        .build()
    g = wf.PipeGraph("reduce_dense_nonkeyed", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(op).add_sink(snk)
    g.run()
    exp = [max(t["v"] for t in stream[lo:lo + 64])
           for lo in range(0, 256, 64)]
    assert got == exp


def test_single_chip_dense_drop_warns_once_and_notes_stats():
    """ADVICE r5 low (ops/tpu.py): adding withMaxKeys + withMonoidCombiner
    for speed silently switches ReduceTPU from the sorted path (keeps
    arbitrary int32 keys) to the dense-table contract (out-of-range keys
    dropped).  The FIRST observed drop must surface one RuntimeWarning
    plus a persistent note in dump_stats — and only once."""
    import warnings
    stream = [{"key": (17 if i % 5 == 0 else i % 4), "v": -1.0 - i}
              for i in range(256)]
    with pytest.warns(RuntimeWarning, match="dense-table contract") as rec:
        _, op = _run_reduce_graph(stream, declare=True, max_keys=4)
        st = op.dump_stats()
    assert sum("dense-table" in str(w.message) for w in rec) == 1
    assert st["Out_of_range_keys_dropped"] == \
        sum(1 for t in stream if t["key"] >= 4)
    assert "dense-table contract" in st["Out_of_range_keys_note"]
    with warnings.catch_warnings():        # warned once, never again
        warnings.simplefilter("error", RuntimeWarning)
        st2 = op.dump_stats()
    assert "dense-table contract" in st2["Out_of_range_keys_note"]


def test_single_chip_dense_no_drop_no_warning():
    """In-range streams must stay silent: no warning, no stats note."""
    import warnings
    stream = [{"key": i % 4, "v": -1.0 - i} for i in range(256)]
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        _, op = _run_reduce_graph(stream, declare=True, max_keys=4)
        st = op.dump_stats()
    assert st["Out_of_range_keys_dropped"] == 0
    assert "Out_of_range_keys_note" not in st
