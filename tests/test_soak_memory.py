"""Memory-bounded long-run soak: a million tuples through the columnar
TPU pipeline (ingest → fused map/filter → TB windows → columnar sink) must
not grow RSS unboundedly — catches leaked device buffers, unbounded pane
rings, or history accumulating in emitters/collectors (the reference's
recycling pools bound memory the same way; here XLA buffer lifetime +
fixed-capacity state carry the guarantee)."""

import os
import sys

import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.io import FrameSource


def _rss_kb() -> int:
    with open("/proc/self/statm") as f:
        return int(f.read().split()[1]) * (os.sysconf("SC_PAGESIZE") // 1024)


@pytest.mark.skipif(sys.platform != "linux", reason="/proc RSS sampling")
def test_soak_rss_bounded():
    n_tuples, cap, n_keys = 1_048_576, 32_768, 64
    rng = np.random.default_rng(5)
    rec = np.empty(n_tuples, dtype=[("k", "<i8"), ("t", "<i8"),
                                    ("v", "<f8")])
    rec["k"] = rng.integers(0, n_keys, n_tuples)
    rec["t"] = np.arange(n_tuples, dtype=np.int64) * 100   # 100 µs apart
    rec["v"] = rng.random(n_tuples)
    blob = rec.tobytes()

    samples = []

    def chunks():
        for lo in range(0, len(blob), 1 << 20):
            samples.append(_rss_kb())
            yield blob[lo:lo + (1 << 20)]

    rows = [0]
    src = FrameSource(chunks, nv=1, fmt="frames", output_batch_size=cap)
    m = wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "v0": t["v0"] * 2.0}).build()
    f = wf.FilterTPU_Builder(lambda t: t["v0"] >= 0.5).build()
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v0"], lambda a, b: a + b)
         .withTBWindows(1_000_000, 250_000)
         .withKeyBy(lambda t: t["key"]).withMaxKeys(n_keys).build())
    snk = (wf.Sink_Builder(
            lambda c: rows.__setitem__(0, rows[0] + len(c))
            if c is not None else None)
           .withColumnarSink().build())
    g = wf.PipeGraph("soak", wf.ExecutionMode.DEFAULT, wf.TimePolicy.EVENT)
    pipe = g.add_source(src)
    pipe.add(m)
    pipe.chain(f)
    pipe.add(w).add_sink(snk)
    g.run()

    assert rows[0] > 10_000          # windows really flowed
    # steady-state RSS growth: compare the 2nd quarter's mean to the last
    # quarter's (the first quarter includes compilation + arena growth)
    q = len(samples) // 4
    early = sum(samples[q:2 * q]) / q
    late = sum(samples[-q:]) / q
    growth_mb = (late - early) / 1024
    assert growth_mb < 256, (early, late, growth_mb)
