"""Memory-bounded long-run soak: a million tuples through the columnar
TPU pipeline (ingest → fused map/filter → TB windows → columnar sink) must
not grow RSS unboundedly — catches leaked device buffers, unbounded pane
rings, or history accumulating in emitters/collectors (the reference's
recycling pools bound memory the same way; here XLA buffer lifetime +
fixed-capacity state carry the guarantee)."""

import os
import sys
import threading

import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.io import FrameSource


def _rss_kb() -> int:
    with open("/proc/self/statm") as f:
        return int(f.read().split()[1]) * (os.sysconf("SC_PAGESIZE") // 1024)


@pytest.mark.slow  # ~11s: the 1M-tuple driver-path RSS soak rides the
# nightly leg next to its host-pool sibling below (wfverify-round
# headroom pass)
@pytest.mark.skipif(sys.platform != "linux", reason="/proc RSS sampling")
def test_soak_rss_bounded():
    n_tuples, cap, n_keys = 1_048_576, 32_768, 64
    rng = np.random.default_rng(5)
    rec = np.empty(n_tuples, dtype=[("k", "<i8"), ("t", "<i8"),
                                    ("v", "<f8")])
    rec["k"] = rng.integers(0, n_keys, n_tuples)
    rec["t"] = np.arange(n_tuples, dtype=np.int64) * 100   # 100 µs apart
    rec["v"] = rng.random(n_tuples)
    blob = rec.tobytes()

    samples = []

    def chunks():
        for lo in range(0, len(blob), 1 << 20):
            samples.append(_rss_kb())
            yield blob[lo:lo + (1 << 20)]

    rows = [0]
    src = FrameSource(chunks, nv=1, fmt="frames", output_batch_size=cap)
    m = wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "v0": t["v0"] * 2.0}).build()
    f = wf.FilterTPU_Builder(lambda t: t["v0"] >= 0.5).build()
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v0"], lambda a, b: a + b)
         .withTBWindows(1_000_000, 250_000)
         .withKeyBy(lambda t: t["key"]).withMaxKeys(n_keys).build())
    snk = (wf.Sink_Builder(
            lambda c: rows.__setitem__(0, rows[0] + len(c))
            if c is not None else None)
           .withColumnarSink().build())
    g = wf.PipeGraph("soak", wf.ExecutionMode.DEFAULT, wf.TimePolicy.EVENT)
    pipe = g.add_source(src)
    pipe.add(m)
    pipe.chain(f)
    pipe.add(w).add_sink(snk)
    g.run()

    assert rows[0] > 10_000          # windows really flowed
    # steady-state RSS growth: compare the 2nd quarter's mean to the last
    # quarter's (the first quarter includes compilation + arena growth)
    q = len(samples) // 4
    early = sum(samples[q:2 * q]) / q
    late = sum(samples[-q:]) / q
    growth_mb = (late - early) / 1024
    assert growth_mb < 256, (early, late, growth_mb)


@pytest.mark.slow  # ~60s: the pool-path soak rides the nightly run; the
# driver-loop soak above plus test_host_pool.py keep tier-1 coverage
@pytest.mark.skipif(sys.platform != "linux", reason="/proc RSS sampling")
def test_soak_rss_bounded_host_pool():
    """Host-pipeline soak under the worker pool: half a million tuples
    through Source -> keyed FlatMap -> KeyedWindows -> Sink with 4 pool
    threads; RSS must stay bounded (catches queue pileups or per-sweep
    future/descriptor leaks in the pool path) and counts must be exact."""
    n_tuples, n_keys = 524_288, 64
    samples = []

    def gen():
        for i in range(n_tuples):
            if i % 65_536 == 0:
                samples.append(_rss_kb())
            yield {"k": i % n_keys, "v": 1}

    got = [0, 0]
    lock = threading.Lock()

    def sink(r):
        if r is not None:
            with lock:
                got[0] += 1
                got[1] += int(r.value)

    cfg = wf.Config(host_worker_threads=4)
    g = wf.PipeGraph("soak_pool", wf.ExecutionMode.DEFAULT, config=cfg)
    g.add_source(wf.Source_Builder(gen).withOutputBatchSize(512).build()) \
     .add(wf.FlatMap_Builder(lambda t, s: s.push(t))
          .withKeyBy(lambda t: t["k"]).withParallelism(4).build()) \
     .add(wf.Keyed_Windows_Builder(lambda t, acc: (acc or 0) + t["v"])
          .withCBWindows(64, 64).withKeyBy(lambda t: t["k"])
          .withParallelism(4).build()) \
     .add_sink(wf.Sink_Builder(sink).withParallelism(2).build())
    g.run()

    # tumbling 64/64 over n/keys tuples per key: every window sums to 64
    per_key = n_tuples // n_keys
    assert got[0] == n_keys * (per_key // 64)
    assert got[1] == got[0] * 64
    q = max(1, len(samples) // 4)
    early = sum(samples[q:2 * q]) / q
    late = sum(samples[-q:]) / q
    assert (late - early) / 1024 < 128, (early, late)
