"""Flight-recorder observability contracts (docs/OBSERVABILITY.md):
log-bucket histogram percentile math on its edge cases, trace-export JSON
schema validity, watermark-lag gauge behavior under punctuation-only flow,
transfer byte counters, real termination state, the recorder-disabled
zero-event guarantee, and the recorder's overhead budget."""

import dataclasses
import json
import os
import subprocess
import sys
import time

import pytest

import windflow_tpu as wf
from windflow_tpu.basic import default_config
from windflow_tpu.monitoring.recorder import (STAGE_NAMES, FlightRecorder,
                                              LatencyHistogram, ReplicaRing,
                                              chrome_trace_from_events)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# LatencyHistogram: percentile math edge cases
# ---------------------------------------------------------------------------

def test_histogram_empty():
    h = LatencyHistogram()
    assert h.percentile(0.5) == 0.0
    q = h.quantiles()
    assert q["count"] == 0
    assert q["p50"] == q["p95"] == q["p99"] == 0.0 and q["max"] == 0.0


def test_histogram_single_sample_is_exact():
    h = LatencyHistogram()
    h.add(137.0)
    # clamping to the observed [min, max] makes one sample report itself,
    # not its log bucket's midpoint
    for p in (0.0, 0.5, 0.95, 0.99, 1.0):
        assert h.percentile(p) == 137.0
    assert h.quantiles()["count"] == 1
    assert h.mean() == 137.0


def test_histogram_bucket_boundaries():
    h = LatencyHistogram()
    # 2^k sits exactly on a bucket edge: [2^(k-1), 2^k) vs [2^k, 2^(k+1))
    for v in (0, 1, 2, 255, 256, 257):
        h.add(v)
    assert h.count == 6
    assert h.min == 0 and h.max == 257
    # percentiles are monotone in p and clamped to the sample range
    last = -1.0
    for p in (0.1, 0.5, 0.9, 0.99):
        v = h.percentile(p)
        assert 0 <= v <= 257
        assert v >= last
        last = v


def test_histogram_percentiles_bracket_distribution():
    h = LatencyHistogram()
    for i in range(1000):
        h.add(float(i))
    p50, p95, p99 = (h.percentile(p) for p in (0.50, 0.95, 0.99))
    assert p50 <= p95 <= p99 <= h.max
    # log buckets guarantee only factor-of-2 resolution: the true p50 of
    # 0..999 is ~500, inside the [256, 1024) bucket span
    assert 256 <= p50 < 1024
    assert p99 > 500


def test_histogram_merge():
    a, b = LatencyHistogram(), LatencyHistogram()
    a.add(10)
    b.add(1000)
    a.merge(b)
    assert a.count == 2
    assert a.min == 10 and a.max == 1000
    assert a.percentile(0.01) >= 10 and a.percentile(0.99) <= 1000


def test_ring_wraps_without_allocation():
    r = ReplicaRing("op", 0, 16)
    for i in range(40):
        r.record(i, 0, 1000 + i)
    ev = r.events()
    assert len(ev) == 16                       # ring capacity retained
    assert ev[0]["trace"] == 24 and ev[-1]["trace"] == 39  # newest kept
    assert r.n == 40


def test_recorder_sampling_rate():
    fr = FlightRecorder(sample_every=4)
    picks = [fr.maybe_trace() for _ in range(40)]
    assert sum(t is not None for t in picks) == 10
    ids = [t[0] for t in picks if t is not None]
    assert ids == sorted(ids) and len(set(ids)) == len(ids)


# ---------------------------------------------------------------------------
# pipeline helpers
# ---------------------------------------------------------------------------

def _tpu_graph(cfg=None, n=4000, cap=512, name="obs_app"):
    src = (wf.Source_Builder(
        lambda: iter({"key": i % 8, "v": float(i)} for i in range(n)))
        .withName("src").withOutputBatchSize(cap).build())
    m = (wf.MapTPU_Builder(lambda t: {"key": t["key"], "v": t["v"] * 2.0})
         .withName("mtpu").build())
    seen = []
    snk = (wf.Sink_Builder(lambda t, ctx=None: seen.append(t))
           .withName("snk").build())
    g = wf.PipeGraph(name, wf.ExecutionMode.DEFAULT, config=cfg)
    g.add_source(src).add(m).add_sink(snk)
    return g, seen


def _traced_cfg(**kw):
    kw.setdefault("flight_recorder", True)
    kw.setdefault("trace_sample_every", 2)
    return dataclasses.replace(default_config, **kw)


# ---------------------------------------------------------------------------
# stats schema: percentiles, byte counters, termination state
# ---------------------------------------------------------------------------

def test_stats_latency_and_byte_totals():
    g, _ = _tpu_graph(cfg=_traced_cfg())
    g.run()
    st = g.stats()
    # h2d wired from the staging plane, d2h from the TPU->host boundary:
    # both totals are real (nonzero) on a staged run
    assert st["Bytes_H2D_total"] > 0
    assert st["Bytes_D2H_total"] > 0
    lat = st["Latency"]
    for op_name in ("src", "mtpu", "snk"):
        q = lat["service_usec_per_operator"][op_name]
        assert set(q) >= {"count", "p50", "p95", "p99"}
    assert lat["end_to_end_usec"]["count"] > 0
    assert 0 < lat["end_to_end_usec"]["p50"] \
        <= lat["end_to_end_usec"]["p99"]
    # per-replica JSON carries the histogram quantiles too
    mtpu = next(o for o in st["Operators"]
                if o["Operator_name"] == "mtpu")
    rj = mtpu["Replicas"][0]
    assert rj["Service_latency_usec"]["count"] > 0
    assert rj["Bytes_H2D"] == 0          # staging credits the UPSTREAM rep
    src_rep = next(o for o in st["Operators"]
                   if o["Operator_name"] == "src")["Replicas"][0]
    assert src_rep["Bytes_H2D"] > 0


def test_is_terminated_reports_actual_state():
    g, _ = _tpu_graph(cfg=_traced_cfg())
    g.start()
    st = g.stats()
    reps = [r for o in st["Operators"] for r in o["Replicas"]]
    assert all(r["Is_terminated"] is False for r in reps)
    g.wait_end()
    st = g.stats()
    reps = [r for o in st["Operators"] for r in o["Replicas"]]
    assert all(r["Is_terminated"] is True for r in reps)


def test_flight_recorder_summary_and_spans():
    g, _ = _tpu_graph(cfg=_traced_cfg())
    g.run()
    fr = g.stats()["Flight_recorder"]
    assert fr["enabled"] is True
    assert fr["traces_started"] > 0
    assert fr["events_recorded"] >= 3 * fr["traces_started"]  # >=3 stages
    stages = {e["stage"] for e in g._recorder.events()}
    assert {"staged", "dispatched", "collected", "sunk"} <= stages
    assert stages <= set(STAGE_NAMES)


def test_device_done_sync_sampling():
    g, _ = _tpu_graph(cfg=_traced_cfg(trace_sample_every=1,
                                      trace_device_sync_every=2),
                      n=4000, cap=256)
    g.run()
    ev = g._recorder.events()
    done = [e for e in ev if e["stage"] == "device_done"]
    dispatched = [e for e in ev if e["stage"] == "dispatched"]
    assert dispatched, "TPU op recorded no dispatches"
    # every 2nd traced batch syncs: roughly half the dispatches, never all
    assert 0 < len(done) <= len(dispatched)


# ---------------------------------------------------------------------------
# trace export
# ---------------------------------------------------------------------------

def test_dump_trace_chrome_schema(tmp_path):
    g, _ = _tpu_graph(cfg=_traced_cfg())
    g.run()
    path = g.dump_trace(str(tmp_path / "app_trace.json"))
    with open(path) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    assert isinstance(evs, list) and evs
    phases = {e["ph"] for e in evs}
    assert "i" in phases and "b" in phases and "e" in phases
    for e in evs:
        assert "name" in e and "ph" in e and "pid" in e
        if e["ph"] != "M":
            assert isinstance(e["ts"], (int, float))
    # async span begin/end pairs balance per (id, name)
    opens = {}
    for e in evs:
        if e["ph"] == "b":
            opens[(e["id"], e["name"])] = opens.get(
                (e["id"], e["name"]), 0) + 1
        elif e["ph"] == "e":
            opens[(e["id"], e["name"])] = opens.get(
                (e["id"], e["name"]), 0) - 1
    assert all(v == 0 for v in opens.values())
    # raw events dumped alongside for offline re-export
    assert (tmp_path / "app_events.json").exists()


def test_trace_export_tool_roundtrip(tmp_path):
    g, _ = _tpu_graph(cfg=_traced_cfg())
    g.run()
    g.dump_trace(str(tmp_path / "app_trace.json"))
    out = tmp_path / "re_trace.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_export.py"),
         str(tmp_path / "app_events.json"), "-o", str(out)],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_export.py"),
         "--check", str(out)], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout


def test_chrome_trace_from_events_empty():
    t = chrome_trace_from_events([])
    assert t["traceEvents"] == []


# ---------------------------------------------------------------------------
# gauges
# ---------------------------------------------------------------------------

def test_watermark_lag_gauge_monotone_under_punctuation_only_flow():
    """An idle-but-live INGRESS source advances its watermark by cadence
    punctuations alone; the frontier gauge must be monotone and the lag
    gauge bounded by the punctuation interval (plus scheduling slack)."""
    def idle_gen():
        for _ in range(4000):
            yield None              # live source, no data

    cfg = dataclasses.replace(default_config,
                              punctuation_interval_usec=5_000)
    src = wf.Source_Builder(idle_gen).withName("idle").build()
    snk = wf.Sink_Builder(lambda t, ctx=None: None).withName("snk").build()
    g = wf.PipeGraph("punct_only", wf.ExecutionMode.DEFAULT, config=cfg)
    g.add_source(src).add_sink(snk)
    g.start()
    fronts = []
    deadline = time.monotonic() + 10.0
    while not g.is_done() and time.monotonic() < deadline:
        g.step()
        gau = g.gauges()
        snk_g = gau["operators"]["snk"]
        if snk_g["watermark_frontier_usec"] is not None:
            fronts.append(snk_g["watermark_frontier_usec"])
            assert snk_g["watermark_lag_usec"] >= 0
        time.sleep(0.001)
    g.wait_end()
    assert len(fronts) > 3, "punctuations never advanced the sink frontier"
    assert fronts == sorted(fronts), "watermark frontier went backwards"
    assert fronts[-1] > fronts[0], "frontier never advanced while idle"


def test_gauges_shape_and_rolling_throughput():
    g, _ = _tpu_graph(cfg=_traced_cfg())
    g.start()
    while not g.is_done():
        g.step()
        g.sample_gauges()
    g.wait_end()
    gau = g.stats()["Gauges"]
    assert set(gau) >= {"operators", "staging_pool_held_bytes",
                        "throughput_1s_tps", "throughput_10s_tps"}
    for name in ("src", "mtpu", "snk"):
        og = gau["operators"][name]
        assert og["queue_depth"] >= 0
    assert gau["throughput_1s_tps"] >= 0.0


def test_gauges_in_dashboard_report_payload():
    """The monitoring thread ships stats() as NEW_REPORT; the payload must
    carry the new observability sections (wire parity is covered by
    test_monitoring.py's stub dashboard — here we check the payload)."""
    g, _ = _tpu_graph(cfg=_traced_cfg())
    g.run()
    payload = json.loads(json.dumps(g.stats()))   # must be JSON-clean
    assert "Gauges" in payload and "Latency" in payload
    assert "Flight_recorder" in payload
    assert payload["Flight_recorder"]["enabled"] is True


# ---------------------------------------------------------------------------
# recorder off: zero events, no trace lanes, no measurable hot-path cost
# ---------------------------------------------------------------------------

def test_recorder_disabled_emits_zero_events():
    cfg = dataclasses.replace(default_config, flight_recorder=False)
    seen_traces = []
    src = (wf.Source_Builder(
        lambda: iter({"key": i % 8, "v": float(i)} for i in range(3000)))
        .withName("src").withOutputBatchSize(256).build())
    m = (wf.MapTPU_Builder(lambda t: {"key": t["key"], "v": t["v"] + 1})
         .withName("mtpu").build())
    snk = (wf.Sink_Builder(lambda t, ctx=None: None)
           .withName("snk").build())
    g = wf.PipeGraph("off_app", wf.ExecutionMode.DEFAULT, config=cfg)
    g.add_source(src).add(m).add_sink(snk)
    g.start()
    # hook the sink inbox to observe trace lanes on in-flight batches
    snk_rep = snk.replicas[0]
    orig = snk_rep.receive

    def spy(ch, msg):
        seen_traces.append(getattr(msg, "trace", None))
        orig(ch, msg)
    snk_rep.receive = spy
    g.wait_end()
    assert g._recorder is None
    assert all(rep.ring is None for rep in g._all_replicas)
    assert all(t is None for t in seen_traces)
    st = g.stats()
    assert st["Flight_recorder"] == {"enabled": False}
    assert st["Latency"]["end_to_end_usec"]["count"] == 0
    # byte counters stay real even with the recorder off
    assert st["Bytes_H2D_total"] > 0
    with pytest.raises(wf.WindFlowError):
        g.dump_trace()


def test_recorder_overhead_within_budget():
    """Overhead smoke (documented budget <2% at default 1-in-64 sampling):
    recorder on vs off over the same pipeline.  CPU CI timing is noisy, so
    the assertion leaves generous slack — it exists to catch a recorder
    that lands on the per-TUPLE path (orders of magnitude, not percent)."""
    def run_once(enabled):
        cfg = dataclasses.replace(default_config,
                                  flight_recorder=enabled,
                                  trace_sample_every=64)
        g, _ = _tpu_graph(cfg=cfg, n=40000, cap=1024,
                          name=f"ovh_{enabled}")
        t0 = time.perf_counter()
        g.run()
        return time.perf_counter() - t0

    run_once(True)                      # warm compile caches for shapes
    on = min(run_once(True) for _ in range(3))
    off = min(run_once(False) for _ in range(3))
    assert on < off * 1.5 + 0.25, \
        f"recorder-on run {on:.3f}s vs off {off:.3f}s exceeds budget slack"
