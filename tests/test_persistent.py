"""Persistent operator suite tests (reference ``tests/rocksdb_tests/``):
the same metamorphic-oracle style as the graph tests, plus KV-store
durability — state must survive a close/reopen, and persistent windows must
produce identical results to in-memory windows while actually spilling
fragments."""

import pickle
import random

import pytest

import windflow_tpu as wf
from windflow_tpu.persistent import (DBHandle, LogKV, PKeyedWindows,
                                     P_Keyed_Windows_Builder, P_Map_Builder,
                                     P_Reduce_Builder, P_Sink_Builder,
                                     SpillingArchive)
from windflow_tpu.persistent.kv import _PyKV
from windflow_tpu.windows.engine import WindowSpec


# ---------------------------------------------------------------------------
# KV store
# ---------------------------------------------------------------------------

def test_kv_roundtrip_and_reopen(tmp_path):
    path = str(tmp_path / "store")
    kv = LogKV(path)
    kv.put(b"a", b"1")
    kv.put(b"b", b"x" * 10_000)
    kv.put(b"a", b"2")          # overwrite
    kv.delete(b"missing")
    assert kv.get(b"a") == b"2"
    assert kv.get(b"b") == b"x" * 10_000
    assert kv.get(b"nope") is None
    assert len(kv) == 2
    kv.put(b"c", b"3")
    kv.delete(b"b")
    assert sorted(kv.keys()) == [b"a", b"c"]
    kv.flush()
    kv.close()
    # reopen: index rebuilt from the log, tombstone honored
    kv2 = LogKV(path)
    assert kv2.get(b"a") == b"2"
    assert kv2.get(b"b") is None
    assert kv2.get(b"c") == b"3"
    kv2.close(delete_db=True)
    kv3 = LogKV(path)           # deleted: fresh store
    assert len(kv3) == 0
    kv3.close(delete_db=True)


def test_kv_compaction_reclaims_space(tmp_path):
    path = str(tmp_path / "store")
    kv = LogKV(path)
    for i in range(200):
        kv.put(b"hot", b"v%d" % i)   # 199 dead versions
    before = kv.log_bytes()
    kv.compact()
    assert kv.log_bytes() < before
    assert kv.get(b"hot") == b"v199"
    assert len(kv) == 1
    kv.close(delete_db=True)


def test_kv_python_fallback_reads_native_format(tmp_path):
    """The pure-Python backend speaks the same on-disk format as the native
    store, so a DB written by one opens under the other."""
    path = str(tmp_path / "store")
    kv = LogKV(path)             # native backend when the toolchain is up
    kv.put(b"k1", b"v1")
    kv.put(b"k2", bytes(range(256)))
    kv.delete(b"k1")
    kv.flush()
    kv.close()
    py = _PyKV(path)
    assert py.get(b"k1") is None
    assert py.get(b"k2") == bytes(range(256))
    py.put(b"k3", b"from_python")
    py.close()
    back = LogKV(path)
    assert back.get(b"k3") == b"from_python"
    back.close(delete_db=True)


def _kv_live_map(kv):
    return {k: kv.get(k) for k in kv.keys()}


def _require_native():
    """Backend parity needs BOTH backends — a self-comparison would pass
    green without testing the claim, hiding the coverage hole."""
    from windflow_tpu import native
    if not native.is_available():
        pytest.skip("native wf_kv unavailable: backend-parity fuzz "
                    "needs both KV backends")


def _kv_open_both(tmp_path, raw, i):
    """Open the same byte image under BOTH backends (each gets its own
    copy: open-time recovery truncates the file in place) and return the
    two live maps plus the recovered log lengths."""
    from windflow_tpu.persistent.kv import _NativeKV, _PyKV
    p_py = str(tmp_path / f"fz_py_{i}")
    with open(p_py, "wb") as f:
        f.write(raw)
    py = _PyKV(p_py)
    py_map, py_end = _kv_live_map(py), py.log_bytes()
    py.close(delete_db=True)
    p_nat = str(tmp_path / f"fz_nat_{i}")
    with open(p_nat, "wb") as f:
        f.write(raw)
    nat = _NativeKV(p_nat)
    nat_map, nat_end = _kv_live_map(nat), nat.log_bytes()
    nat.close(delete_db=True)
    return py_map, py_end, nat_map, nat_end


def test_kv_crash_consistency_fuzz_backend_parity(tmp_path):
    """Crash-consistency fuzz (durability satellite): truncate a written
    DB at EVERY byte offset — the torn-tail image any mid-append crash
    can leave — and assert ``_PyKV`` and ``_NativeKV`` recover the SAME
    live prefix (the backend-parity claim in persistent/kv.py's
    docstring, previously never cross-tested under torn tails).  The
    durability plane's manifest-commit protocol rests on exactly this
    equivalence: an epoch exists iff its manifest record survives
    recovery, under either backend."""
    _require_native()
    from windflow_tpu.persistent.kv import _PyKV
    path = str(tmp_path / "ref")
    kv = _PyKV(path)   # deterministic byte image: pure-Python writer
    kv.put(b"a", b"1")
    kv.put(b"bb", b"x" * 37)
    kv.put(b"a", b"2")               # overwrite
    kv.delete(b"bb")                 # tombstone
    kv.put(b"ccc", bytes(range(64)))
    kv.put(b"d" * 9, b"")            # empty value
    kv.flush()
    raw = open(path, "rb").read()
    kv.close(delete_db=True)
    assert len(raw) < 400            # keeps the every-offset sweep cheap
    prev_py = None
    for cut in range(len(raw) + 1):
        py_map, py_end, nat_map, nat_end = _kv_open_both(
            tmp_path, raw[:cut], cut)
        assert py_map == nat_map, (
            f"backends recover different live sets at cut={cut}: "
            f"py={sorted(py_map)} native={sorted(nat_map)}")
        assert py_end == nat_end, (
            f"backends truncate to different recovery points at "
            f"cut={cut}: py={py_end} native={nat_end}")
        assert py_end <= cut          # recovery never invents bytes
        if prev_py is not None:
            # live entries only ever grow as more log survives — a
            # shorter prefix can't know MORE than a longer one, except
            # where the extra record was an overwrite or tombstone
            assert len(py_map) >= len(prev_py) - 1
        prev_py = py_map
    # full image recovers the reference content under both backends
    py_map, _, nat_map, _ = _kv_open_both(tmp_path, raw, "full")
    assert py_map == nat_map == {b"a": b"2",
                                 b"ccc": bytes(range(64)),
                                 b"d" * 9: b""}


def test_kv_corruption_fuzz_backend_parity(tmp_path):
    """Flip one byte at every offset of a written DB and assert both
    backends stop (or survive) at the SAME recovery point with the same
    live entries — corruption anywhere must never make the two stores
    diverge about what exists."""
    _require_native()
    from windflow_tpu.persistent.kv import _PyKV
    path = str(tmp_path / "ref")
    kv = _PyKV(path)
    kv.put(b"k1", b"alpha")
    kv.put(b"k2", b"beta" * 8)
    kv.delete(b"k1")
    kv.put(b"k3", b"gamma")
    kv.flush()
    raw = bytearray(open(path, "rb").read())
    kv.close(delete_db=True)
    for off in range(len(raw)):
        corrupt = bytes(raw[:off]) + bytes([raw[off] ^ 0xFF]) \
            + bytes(raw[off + 1:])
        py_map, py_end, nat_map, nat_end = _kv_open_both(
            tmp_path, corrupt, f"c{off}")
        assert py_map == nat_map, (
            f"backends diverge on corruption at offset {off}: "
            f"py={sorted(py_map)} native={sorted(nat_map)}")
        assert py_end == nat_end, (
            f"recovery points diverge on corruption at offset {off}: "
            f"py={py_end} native={nat_end}")


def test_db_handle_typed_keys_and_initial_state(tmp_path):
    db = DBHandle(str(tmp_path / "db"), initial_state=lambda: {"n": 0},
                  delete_db=False)
    assert db.get(42) == {"n": 0}          # unseen key: fresh initial state
    s = db.get("alpha")
    s["n"] = 7
    db.put("alpha", s)
    db.put((1, "compound"), {"n": 3})
    assert db.get("alpha") == {"n": 7}
    assert db.lookup("beta") is None
    assert sorted(map(str, db.keys())) == sorted(
        map(str, ["alpha", (1, "compound")]))
    db.close()
    # initial_state factories must produce independent states
    db2 = DBHandle(str(tmp_path / "db2"), initial_state={"n": 0})
    a, b = db2.get(1), db2.get(2)
    a["n"] = 99
    assert b["n"] == 0
    db2.close()


# ---------------------------------------------------------------------------
# Persistent operators in graphs
# ---------------------------------------------------------------------------

def _stream(n_keys, length):
    return [{"key": i % n_keys, "value": i} for i in range(length)]


class Acc:
    def __init__(self):
        self.total = 0
        self.count = 0

    def __call__(self, item, ctx=None):
        if item is not None:
            self.total += int(item["value"])
            self.count += 1


def run_pmap_pipeline(tmp_path, par, run_id, length=400, n_keys=6):
    """P_Map counts per-key occurrences in its persistent state and stamps
    the running count onto each tuple."""
    acc = Acc()

    def stamp(t, state):
        state["seen"] = state.get("seen", 0) + 1
        return {"key": t["key"], "value": t["value"] + state["seen"]}

    src = (wf.Source_Builder(lambda: iter(_stream(n_keys, length)))
           .withName("src").build())
    pm = (P_Map_Builder(stamp).withName("pmap").withParallelism(par)
          .withKeyBy(lambda t: t["key"])
          .withDBPath(str(tmp_path / f"pmap_db_{run_id}"))
          .withInitialState(dict).build())
    snk = wf.Sink_Builder(acc).withName("sink").build()
    g = wf.PipeGraph(f"p_map_{run_id}", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(pm).add_sink(snk)
    g.run()
    return acc


def test_p_map_metamorphic(tmp_path):
    reference = None
    rnd = random.Random(3)
    for run in range(4):
        par = rnd.randint(1, 4)
        acc = run_pmap_pipeline(tmp_path, par, run)
        if reference is None:
            reference = (acc.total, acc.count)
        else:
            assert (acc.total, acc.count) == reference, f"par={par} diverged"
    # oracle: per key, counts stamp 1..occurrences
    length, n_keys = 400, 6
    occ = length // n_keys
    extra = length % n_keys
    expected = sum(range(length))
    for k in range(n_keys):
        n = occ + (1 if k < extra else 0)
        expected += n * (n + 1) // 2
    assert reference[0] == expected


def test_p_reduce_state_survives_restart(tmp_path):
    """withKeepDb: a second run resumes from the first run's keyed state —
    the durability the reference gets from keeping the RocksDB path."""
    db_path = str(tmp_path / "counts")
    results = {}

    def count(t, state):
        state["n"] = state.get("n", 0) + 1

    def grab(item, ctx=None):
        if item is not None:
            results[item.get("key", None) if isinstance(item, dict)
                    else None] = item

    def run_once():
        src = (wf.Source_Builder(lambda: iter(_stream(4, 100)))
               .withName("src").build())
        red = (P_Reduce_Builder(count).withName("preduce")
               .withKeyBy(lambda t: t["key"])
               .withDBPath(db_path).withInitialState(dict)
               .withKeepDb().build())
        snk = wf.Sink_Builder(lambda t, ctx=None: None).withName("s").build()
        g = wf.PipeGraph("p_reduce", wf.ExecutionMode.DEFAULT)
        g.add_source(src).add(red).add_sink(snk)
        g.run()

    run_once()
    run_once()  # second run: counts continue from the first
    db = DBHandle(db_path, initial_state=dict, delete_db=False, whoami=0)
    total = sum(db.get(k)["n"] for k in db.keys())
    db.close()
    assert total == 200  # 100 tuples per run, resumed not reset


def test_p_sink_eos_and_state(tmp_path):
    calls = {"eos": 0, "items": 0}

    def sink_fn(item, state):
        if item is None:
            calls["eos"] += 1
        else:
            calls["items"] += 1
            state["n"] = state.get("n", 0) + 1

    src = wf.Source_Builder(lambda: iter(_stream(3, 30))).withName("s").build()
    snk = (P_Sink_Builder(sink_fn).withName("psink")
           .withKeyBy(lambda t: t["key"]).withParallelism(2)
           .withDBPath(str(tmp_path / "sink_db"))
           .withInitialState(dict).build())
    g = wf.PipeGraph("p_sink", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add_sink(snk)
    g.run()
    assert calls["items"] == 30
    assert calls["eos"] == 2  # one per replica


# ---------------------------------------------------------------------------
# Persistent keyed windows
# ---------------------------------------------------------------------------

def _window_results(op_builder, length=300, n_keys=4, win=20, slide=10):
    got = []

    def grab(r, ctx=None):
        if r is not None:
            got.append((r.key, r.wid, r.value))

    src = (wf.Source_Builder(
        lambda: iter(_stream(n_keys, length)))
        .withName("src").build())
    win_op = (op_builder(lambda items: sum(t["value"] for t in items))
              .withName("win").withCBWindows(win, slide)
              .withKeyBy(lambda t: t["key"]).withParallelism(2).build())
    snk = wf.Sink_Builder(grab).withName("sink").build()
    g = wf.PipeGraph("pwin", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(win_op).add_sink(snk)
    g.run()
    return sorted(got)


def test_p_keyed_windows_match_in_memory(tmp_path):
    """Spilling windows (tiny in-memory buffer forces fragments) produce
    exactly the in-memory KeyedWindows results."""
    expected = _window_results(wf.Keyed_Windows_Builder)
    actual = _window_results(
        lambda fn: (P_Keyed_Windows_Builder(fn)
                    .withDBPath(str(tmp_path / "win_db"))
                    .withMaxInMemoryElements(8)))
    assert actual == expected
    assert len(actual) > 0


def test_spilling_archive_spills_and_reloads(tmp_path):
    db = DBHandle(str(tmp_path / "arch"), delete_db=True)
    arch = SpillingArchive(db, key=7, n_max=4)
    for i in range(19):
        arch.insert((i, i, {"v": i}, i))
    assert arch.spilled_fragments >= 3       # 19 entries, buffers of 4
    assert len(arch) == 19
    got = arch.range(5, 15)
    assert [e[0] for e in got] == list(range(5, 15))
    arch.purge_below(8)                      # fragments fully below 8 die
    assert [e[0] for e in arch.range(0, 100)] == list(range(8, 19))
    arch.clear()
    assert len(arch) == 0
    assert len(db) == 0                      # all fragments deleted
    db.close()


def test_spilling_archive_out_of_order(tmp_path):
    db = DBHandle(str(tmp_path / "arch2"), delete_db=True)
    arch = SpillingArchive(db, key=0, n_max=3)
    order = [5, 1, 9, 2, 8, 0, 7, 3, 6, 4]
    for aid, d in enumerate(order):
        arch.insert((d, aid, d, d))
    got = arch.range(0, 10)
    assert [e[0] for e in got] == sorted(order)
    db.close()
