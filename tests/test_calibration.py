"""Calibration plane (monitoring/calibration.py): the provenance
vocabulary on every surfaced modeled number, the calibration store's
load/degrade contract (device-kind gate, TTL staleness, kill switch),
the live roofline ledger's rate accounting + ROOFLINE_DEGRADED
enter/latch/clear hysteresis, the OpenMetrics/postmortem surfaces, the
wf_calibrate --check exit codes, and the off-path micro-assert.

The honesty property is the plane's contract: a number computed from a
constant must say so (``modeled``), a probe-measured replacement must
carry its age (``calibrated(<age>)``) and must DEGRADE back to the
modeled default — loudly, once — when it goes stale or was recorded on
different hardware.  A dead measurement silently outranking a live
model is exactly the failure mode this plane exists to kill.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time
import types
import warnings

import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.monitoring import calibration as cal

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N = 4096
CAP = 256
KEYS = 8


@pytest.fixture(autouse=True)
def _clean_store():
    """Every test starts and ends uncalibrated: the default store is
    process-global (that is its point), so leakage between tests would
    flip provenance tags in unrelated suites."""
    cal.set_default_store(None)
    yield
    cal.set_default_store(None)


def _store_doc(recorded_at=None, device_kind=None, constants=None,
               jax_version="0.0-test"):
    return {
        "schema": cal.SCHEMA,
        "recorded_at": time.time() if recorded_at is None else recorded_at,
        "device_kind": device_kind or cal.live_device_kind() or "cpu",
        "backend": "cpu",
        "jax_version": jax_version,
        "constants": constants or {
            "ici_bytes_per_sec": 42e9,
            "h2d_tunnel_bytes_per_sec": 1e9,
            "hbm_bytes_per_sec": 5e9,
            "dispatch_overhead_usec": 8.0,
            "sampled_sync_usec": 2.0,
            "kernel_step_usec": 500.0,
        },
    }


def _install(**kw):
    store = cal.CalibrationStore(_store_doc(**kw), path="<test>")
    cal.set_default_store(store)
    return store


# ---------------------------------------------------------------------------
# harness: the latency-plane pipeline (packed frames -> map -> filter ->
# window), driven with health_tick per sweep so the roofline ring fills
# ---------------------------------------------------------------------------

def _frames_blob(n, nkeys=KEYS, seed=11):
    rng = np.random.default_rng(seed)
    rec = np.zeros(n, dtype=[("k", "<i8"), ("ts", "<i8"), ("v", "<f8")])
    rec["k"] = rng.integers(0, nkeys, n)
    rec["ts"] = np.arange(n, dtype=np.int64) * 500
    rec["v"] = rng.random(n)
    return rec.tobytes()


def _source(n=N, cap=CAP):
    blob = _frames_blob(n)
    step = cap * 24

    def chunks():
        for i in range(0, len(blob), step):
            yield blob[i:i + step]

    from windflow_tpu.io.frames import FrameSource
    return FrameSource(chunks, nv=1, fields=["v"], output_batch_size=cap)


def _cfg(**kw):
    kw.setdefault("key_compaction", False)
    return dataclasses.replace(wf.default_config, **kw)


def _graph(cfg, n=N, cap=CAP, name="cal_app"):
    fired = []
    m = (wf.MapTPU_Builder(lambda t: {"key": t["key"], "v": t["v"] * 2.0})
         .withName("m").build())
    f = (wf.FilterTPU_Builder(lambda t: (t["key"] & 7) != 7)
         .withName("f").build())
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"], lambda a, b: a + b)
         .withCBWindows(64, 32).withKeyBy(lambda t: t["key"])
         .withMaxKeys(KEYS).withName("win").build())
    snk = (wf.Sink_Builder(lambda r: fired.append(r) if r is not None
                           else None).withName("snk").build())
    g = wf.PipeGraph(name, config=cfg, time_policy=wf.TimePolicy.EVENT)
    g.add_source(_source(n, cap)).add(m).add(f).add(w).add_sink(snk)
    return g, fired


def _drive(g):
    """step + health_tick per sweep (the monitor cadence, worst case)."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        g.start()
        while not g.is_done():
            if not g.step():
                break
            g.health_tick()
        g.wait_end()
        g.health_tick()


# ---------------------------------------------------------------------------
# provenance vocabulary + store validation
# ---------------------------------------------------------------------------

def test_calibrated_tag_ages_and_vocabulary():
    assert cal.calibrated_tag(90) == "calibrated(90s)"
    assert cal.calibrated_tag(2 * 3600) == "calibrated(2h)"
    assert cal.calibrated_tag(3 * 86400) == "calibrated(3d)"
    for tag in ("measured", "modeled", "interpret",
                cal.calibrated_tag(5)):
        assert cal.legal_provenance(tag), tag
    for tag in ("guessed", "", None, 1.0, "calibrated"):
        assert not cal.legal_provenance(tag), tag


@pytest.mark.parametrize("mutate, msg", [
    (lambda d: d.update(schema="wf-calibration/999"), "schema"),
    (lambda d: d.update(recorded_at="yesterday"), "recorded_at"),
    (lambda d: d.update(device_kind=""), "device_kind"),
    (lambda d: d.update(jax_version=None), "jax_version"),
    (lambda d: d.update(constants={}), "constants"),
    (lambda d: d["constants"].update(warp_drive_factor=9.0), "unknown"),
    (lambda d: d["constants"].update(hbm_bytes_per_sec=float("nan")),
     "finite"),
    (lambda d: d["constants"].update(hbm_bytes_per_sec=-1.0), "finite"),
], ids=["schema", "recorded_at", "device_kind", "jax_version",
        "empty_constants", "unknown_key", "nan", "negative"])
def test_corrupt_store_rejected(mutate, msg):
    doc = _store_doc()
    mutate(doc)
    with pytest.raises(cal.CalibrationError):
        cal.CalibrationStore(doc)


def test_corrupt_file_degrades_graph_build_with_warning(tmp_path):
    bad = tmp_path / "cal.json"
    bad.write_text("{not json")
    cfg = _cfg(calibration=str(bad))
    g, _ = _graph(cfg, n=512, name="cal_bad_app")
    with pytest.warns(RuntimeWarning, match="running uncalibrated"):
        g.start()                       # _build() loads the store
    while not g.is_done():
        if not g.step():
            break
    g.wait_end()
    # the process stays on its modeled defaults
    v, prov = cal.constant("hbm_bytes_per_sec")
    assert prov == "modeled"
    assert v == cal.MODELED_DEFAULTS["hbm_bytes_per_sec"]


# ---------------------------------------------------------------------------
# constant(): the calibrated round trip and every degrade path
# ---------------------------------------------------------------------------

def test_constant_round_trip_flips_value_and_tag():
    v, prov = cal.constant("ici_bytes_per_sec")
    assert prov == "modeled"
    assert v == cal.MODELED_DEFAULTS["ici_bytes_per_sec"]
    _install()
    v, prov = cal.constant("ici_bytes_per_sec")
    assert v == 42e9
    assert cal.is_calibrated(prov)
    v, prov = cal.constant("h2d_tunnel_bytes_per_sec")
    assert (v, cal.is_calibrated(prov)) == (1e9, True)
    # clearing the store restores the modeled default
    cal.set_default_store(None)
    v, prov = cal.constant("ici_bytes_per_sec")
    assert prov == "modeled"
    assert v == cal.MODELED_DEFAULTS["ici_bytes_per_sec"]


def test_constant_missing_key_stays_modeled():
    _install(constants={"hbm_bytes_per_sec": 5e9})
    v, prov = cal.constant("dispatch_overhead_usec")
    assert prov == "modeled"
    assert v == cal.MODELED_DEFAULTS["dispatch_overhead_usec"]


def test_device_kind_mismatch_degrades_with_one_warning():
    _install(device_kind="TPU v99")
    with warnings.catch_warnings(record=True) as wlog:
        warnings.simplefilter("always")
        v, prov = cal.constant("hbm_bytes_per_sec")
        v2, prov2 = cal.constant("ici_bytes_per_sec")
    assert prov == prov2 == "modeled"
    assert v == cal.MODELED_DEFAULTS["hbm_bytes_per_sec"]
    kind_warns = [w for w in wlog if "device kind" in str(w.message)]
    assert len(kind_warns) == 1, "the mismatch warning must fire ONCE"


def test_ttl_staleness_degrades_with_one_warning():
    _install(recorded_at=time.time() - cal.TTL_S - 3600)
    with warnings.catch_warnings(record=True) as wlog:
        warnings.simplefilter("always")
        v, prov = cal.constant("hbm_bytes_per_sec")
        v2, _ = cal.constant("hbm_bytes_per_sec")
    assert prov == "modeled"
    assert v == v2 == cal.MODELED_DEFAULTS["hbm_bytes_per_sec"]
    stale = [w for w in wlog if "days old" in str(w.message)]
    assert len(stale) == 1, "the staleness warning must fire ONCE"
    # freshness is judged at read time: the SAME store read with a
    # clock inside the TTL serves the calibrated value
    v, prov = cal.constant("hbm_bytes_per_sec",
                           now=time.time() - cal.TTL_S - 3000)
    assert (v, cal.is_calibrated(prov)) == (5e9, True)


def test_kill_switch_blocks_config_load(tmp_path, monkeypatch):
    path = tmp_path / "cal.json"
    path.write_text(json.dumps(_store_doc()))
    monkeypatch.setenv("WF_TPU_CALIBRATION", "0")
    assert cal.killed()
    g, _ = _graph(_cfg(calibration=str(path)), n=512, name="cal_kill_app")
    assert cal.default_store() is None
    _, prov = cal.constant("hbm_bytes_per_sec")
    assert prov == "modeled"


def test_provenance_summary_shape():
    _install()
    s = cal.provenance_summary()
    assert s["schema"] == cal.SCHEMA
    assert s["enabled"] is True
    assert set(s["constants"]) == set(cal.MODELED_DEFAULTS)
    for key, slot in s["constants"].items():
        assert cal.legal_provenance(slot["provenance"]), key
        assert cal.is_calibrated(slot["provenance"]), key
    assert s["store"]["fresh"] is True


# ---------------------------------------------------------------------------
# provenance threads through stats(): sweep bytes, shard ICI, tenant
# ICI — and the calibrated store flips the bandwidth tags
# ---------------------------------------------------------------------------

def test_sweep_section_bytes_carry_provenance():
    g, fired = _graph(_cfg(), name="cal_sweep_app")
    _drive(g)
    assert fired
    sweep = g.stats()["Sweep"]
    assert sweep["totals"]["bytes_provenance"] == "modeled"
    hops = [h for h in sweep["per_hop"].values()
            if "bytes_per_tuple" in h]
    assert hops, "no hop attributed bytes"
    for h in hops:
        assert h["bytes_provenance"] == "modeled"
    wire = sweep.get("wire")
    if wire:
        assert wire["bytes_provenance"] == "measured"


def _mesh_graph(n_keys=16):
    from windflow_tpu.parallel import mesh as M
    mesh = M.make_mesh(8, data=2)
    cfg = dataclasses.replace(wf.default_config, mesh=mesh)
    rng = np.random.default_rng(3)
    ks = rng.integers(0, n_keys, 8 * 128)
    src = (wf.Source_Builder(lambda: iter(
        {"key": int(k), "v": float(i)} for i, k in enumerate(ks)))
        .withOutputBatchSize(128).build())
    win = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                      lambda a, b: a + b)
           .withCBWindows(8, 4).withKeyBy(lambda t: t["key"])
           .withMaxKeys(n_keys).withName("mwin").build())
    g = wf.PipeGraph("cal_mesh", wf.ExecutionMode.DEFAULT, config=cfg)
    g.add_source(src).add(win).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    return g


def test_shard_ici_model_provenance_flips_calibrated():
    g = _mesh_graph()
    g.run()
    sec = g.stats()["Shard"]
    ici = sec["per_op"]["mwin"]["ici"]
    # uncalibrated: the structural model divides by the modeled default
    assert ici["provenance"] == "modeled"
    assert ici["ici_bandwidth_provenance"] == "modeled"
    assert ici["ici_bandwidth_assumed_bps"] == \
        cal.MODELED_DEFAULTS["ici_bytes_per_sec"]
    assert sec["totals"]["ici_provenance"] == "modeled"
    assert sec["totals"]["ici_time_provenance"] == "modeled"
    usec_modeled = ici["ici_usec_per_dispatch"]
    # calibrated: the TIME column flips tag AND value; the BYTES half
    # stays structural (the collective shape is derived, not measured)
    _install()
    sec = g.stats()["Shard"]
    ici = sec["per_op"]["mwin"]["ici"]
    assert cal.is_calibrated(ici["ici_bandwidth_provenance"])
    assert ici["ici_bandwidth_assumed_bps"] == 42e9
    assert ici["provenance"] == "modeled"
    assert cal.is_calibrated(sec["totals"]["ici_time_provenance"])
    assert sec["totals"]["ici_provenance"] == "modeled"
    # both readings are rounded to 3 decimals, so compare loosely —
    # the point is the value moved WITH the bandwidth, 90e9 -> 42e9
    expected = usec_modeled * cal.MODELED_DEFAULTS["ici_bytes_per_sec"] \
        / 42e9
    assert ici["ici_usec_per_dispatch"] == pytest.approx(expected,
                                                         rel=0.10)
    assert ici["ici_usec_per_dispatch"] > usec_modeled


def test_tenant_rows_carry_ici_provenance():
    from windflow_tpu.monitoring.tenant_ledger import default_ledger
    default_ledger().reset()
    g = _mesh_graph()
    g.config.tenant = "cal_tenant"
    g.run()
    ten = g.stats()["Tenant"]
    agg = ten["tenants"]["cal_tenant"]
    assert agg["ici_provenance"] == "modeled"
    _install()
    agg = g.stats()["Tenant"]["tenants"]["cal_tenant"]
    assert agg["ici_provenance"] == "modeled"  # bytes stay structural
    default_ledger().reset()


# ---------------------------------------------------------------------------
# roofline ledger: deterministic rate accounting + the verdict machine
# (synthetic graph, synthetic clock — zero weather)
# ---------------------------------------------------------------------------

def _fake_graph(names=("win",), bpt=None):
    ops = []
    for name in names:
        rep = types.SimpleNamespace(
            stats=types.SimpleNamespace(inputs_received=0))
        ops.append(types.SimpleNamespace(name=name, is_tpu=True,
                                         replicas=[rep]))
    ledger = None
    if bpt is not None:
        ledger = types.SimpleNamespace(section=lambda: {
            "per_hop": {n: {"steady_bytes_per_tuple": bpt,
                            "bytes_provenance": "modeled"}
                        for n in names}})
    return types.SimpleNamespace(_operators=ops, _ledger=ledger)


def _feed(led, g, t, rate, ticks, dt=1.0):
    for _ in range(ticks):
        t += dt
        for op in g._operators:
            op.replicas[0].stats.inputs_received += int(rate * dt)
        led.tick(now_s=t)
    return t


def test_roofline_rates_exact_and_telescope_vs_decomposition():
    """The gauge's arithmetic is the bench roofline's: achieved B/s =
    tup/s x B/tuple, ratio = achieved/bandwidth.  On a synthetic clock
    the ring rate is exact, so the telescoped ratio must agree with the
    independently computed decomposition well inside the 10% acceptance
    bound."""
    _install(constants={"hbm_bytes_per_sec": 48000.0})
    g = _fake_graph(bpt=24.0)
    led = cal.RooflineLedger(g)
    _feed(led, g, 0.0, rate=1000.0, ticks=10)
    sec = led.section()
    hop = sec["per_hop"]["win"]
    assert hop["achieved_tuples_per_sec"] == pytest.approx(1000.0)
    assert hop["tuples_per_sec_provenance"] == "measured"
    assert hop["bytes_per_tuple"] == 24.0
    assert hop["bytes_per_tuple_provenance"] == "modeled"
    assert hop["achieved_bytes_per_sec"] == pytest.approx(24000.0)
    assert hop["roofline_tuples_per_sec"] == pytest.approx(2000.0)
    # the telescoping check: ratio from the gauge vs the bench-style
    # decomposition computed independently from its factors
    expected = (1000.0 * 24.0) / 48000.0
    assert hop["ratio_vs_roofline"] == pytest.approx(expected, rel=0.10)
    assert hop["ratio_vs_roofline"] == pytest.approx(0.5, abs=1e-6)
    assert sec["bandwidth_bytes_per_sec"] == 48000.0
    assert cal.is_calibrated(sec["bandwidth_provenance"])
    assert sec["dominant_op"] == "win"


def test_roofline_degraded_enter_latch_clear():
    g = _fake_graph()
    led = cal.RooflineLedger(g)
    # under MIN_SAMPLES: no verdict however bad the rates look
    t = _feed(led, g, 0.0, rate=1000.0, ticks=led.MIN_SAMPLES - 2)
    t = _feed(led, g, t, rate=10.0, ticks=1)
    assert led.verdict is None
    # fill the baseline, then collapse: the FIRST breach tick must not
    # enter (hysteresis), the ENTER_AFTER'th does
    g2 = _fake_graph()
    led2 = cal.RooflineLedger(g2)
    t = _feed(led2, g2, 0.0, rate=1000.0, ticks=led2.MIN_SAMPLES + 2)
    assert led2.verdict is None
    t = _feed(led2, g2, t, rate=100.0, ticks=1)
    assert led2.verdict is None, "entered after one breach tick"
    t = _feed(led2, g2, t, rate=100.0, ticks=1)
    v = led2.verdict
    assert v is not None and led2.entered == 1
    assert v["state"] == "ROOFLINE_DEGRADED"
    assert v["dominant_op"] == "win"
    assert v["ratio_vs_baseline"] < cal.DEGRADE_RATIO
    assert v["baseline_tuples_per_sec"] > v["current_tuples_per_sec"]
    # idle ticks (a drained graph) are NOT recovery: the verdict latches
    for _ in range(5):
        t += 1.0
        led2.tick(now_s=t)
    assert led2.verdict is v, "idle ticks cleared the latch"
    # recovery: CLEAR_AFTER consecutive healthy ticks clear, not fewer
    t = _feed(led2, g2, t, rate=1000.0, ticks=led2.CLEAR_AFTER - 1)
    assert led2.verdict is not None, "cleared early"
    t = _feed(led2, g2, t, rate=1000.0, ticks=1)
    assert led2.verdict is None and led2.cleared == 1
    assert led2.last_verdict is v      # forensics survive the clear


def test_drained_graph_never_latches():
    g = _fake_graph()
    led = cal.RooflineLedger(g)
    t = _feed(led, g, 0.0, rate=1000.0, ticks=led.MIN_SAMPLES + 2)
    # the stream ends: counters freeze, ticks continue (monitor thread)
    for _ in range(20):
        t += 1.0
        led.tick(now_s=t)
    assert led.verdict is None and led.entered == 0


# ---------------------------------------------------------------------------
# live integration: the real pipeline's Roofline section, the health
# verdict attribution, OpenMetrics, webui marker, postmortem + doctor
# ---------------------------------------------------------------------------

def test_roofline_section_on_real_graph(monkeypatch):
    # warm full-suite runs finish in well under the wall-clock tick
    # throttle; zero it so every health_tick samples a rate
    monkeypatch.setattr(cal.RooflineLedger, "TICK_MIN_INTERVAL_S", 0.0)
    g, fired = _graph(_cfg(), name="cal_live_app")
    _drive(g)
    assert fired
    sec = g.stats()["Roofline"]
    assert sec["enabled"]
    assert sec["per_hop"], "no hop ever sampled a rate"
    assert sec["dominant_op"] in sec["per_hop"]
    assert sec["bandwidth_provenance"] == "modeled"
    for name, hop in sec["per_hop"].items():
        assert hop["achieved_tuples_per_sec"] > 0, name
        assert hop["tuples_per_sec_provenance"] == "measured"
        if "bytes_per_tuple" in hop:       # sweep-ledger join
            assert hop["bytes_per_tuple_provenance"] == "modeled"
            assert hop["ratio_vs_roofline"] >= 0
            assert hop["achieved_bytes_per_sec"] == pytest.approx(
                hop["achieved_tuples_per_sec"] * hop["bytes_per_tuple"],
                rel=0.01)
    assert set(sec["calibration"]["constants"]) \
        == set(cal.MODELED_DEFAULTS)
    assert sec["verdict"] is None


def test_roofline_verdict_surfaces_in_health_dominant_op_only():
    g, _ = _graph(_cfg(), name="cal_health_app")
    _drive(g)
    v = {"state": "ROOFLINE_DEGRADED", "dominant_op": "m",
         "current_tuples_per_sec": 10.0,
         "baseline_tuples_per_sec": 1000.0,
         "ratio_vs_baseline": 0.01, "degrade_ratio": 0.5,
         "entered_tick": 9}
    g._roofline.verdict = g._roofline.last_verdict = v
    g.health_tick()
    h = g.stats()["Health"]
    assert h["graph_state"] == "ROOFLINE_DEGRADED"
    for name, hv in h["verdicts"].items():
        if name == "m":
            assert hv["state"] == "ROOFLINE_DEGRADED"
            assert hv["roofline"]["ratio_vs_baseline"] == 0.01
        else:
            assert hv["state"] != "ROOFLINE_DEGRADED"
            assert "roofline" not in hv


def test_openmetrics_roofline_and_provenance_families(monkeypatch):
    from windflow_tpu.monitoring.openmetrics import (parse_exposition,
                                                     render_openmetrics)
    monkeypatch.setattr(cal.RooflineLedger, "TICK_MIN_INTERVAL_S", 0.0)
    _install()
    g, _ = _graph(_cfg(), name="cal_om_app")
    _drive(g)
    fams = parse_exposition(render_openmetrics(g.stats()))
    sec = g.stats()["Roofline"]
    tps = {lab["operator"]: val for _, lab, val in
           fams["wf_roofline_achieved_tuples_per_sec"]["samples"]}
    for name, hop in sec["per_hop"].items():
        assert tps[name] == pytest.approx(
            hop["achieved_tuples_per_sec"], rel=0.5)
    for _, lab, _ in fams["wf_roofline_bytes_per_tuple"]["samples"]:
        assert cal.legal_provenance(lab["provenance"])
    degraded = fams["wf_roofline_degraded"]["samples"]
    assert degraded and degraded[0][2] == 0
    # the info family: one sample per constant, provenance as a label
    prov = {lab["constant"]: lab["provenance"] for _, lab, _ in
            fams["wf_provenance"]["samples"]}
    assert set(prov) == set(cal.MODELED_DEFAULTS)
    assert all(cal.legal_provenance(p) for p in prov.values())
    assert any(p.startswith("calibrated(") for p in prov.values())
    # modeled gauges carry the provenance label
    sweep = fams.get("wf_sweep_bytes_per_tuple")
    assert sweep and sweep["samples"]
    for _, lab, _ in sweep["samples"]:
        assert lab["provenance"] == "modeled"


def test_webui_marks_modeled_cells():
    from windflow_tpu.monitoring.webui import INDEX_HTML
    assert "provenance" in INDEX_HTML
    assert "XLA cost-table estimate" in INDEX_HTML


def _wf_doctor(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_doctor.py"),
         *args], capture_output=True, text=True, timeout=60)


@pytest.fixture()
def cal_bundle(tmp_path):
    _install()
    g, _ = _graph(_cfg(), name="cal_pm_app")
    _drive(g)
    bundle = g.dump_postmortem(str(tmp_path / "pm"), reason="manual")
    with open(os.path.join(bundle, "manifest.json")) as f:
        manifest = json.load(f)
    assert "calibration.json" in manifest["files"]
    assert "roofline.json" in manifest["files"]
    return bundle


def test_postmortem_calibration_roundtrips_wf_doctor(cal_bundle):
    r = _wf_doctor("--check", cal_bundle)
    assert r.returncode == 0, r.stderr
    r = _wf_doctor(cal_bundle)
    assert r.returncode == 0, r.stderr
    assert "calibration:" in r.stdout
    assert "roofline:" in r.stdout
    with open(os.path.join(cal_bundle, "calibration.json")) as f:
        doc = json.load(f)
    assert doc["schema"] == cal.SCHEMA
    for slot in doc["constants"].values():
        assert cal.legal_provenance(slot["provenance"])


def test_wf_doctor_rejects_corrupt_calibration_section(cal_bundle):
    cp = os.path.join(cal_bundle, "calibration.json")
    with open(cp) as f:
        doc = json.load(f)
    doc["constants"]["hbm_bytes_per_sec"]["provenance"] = "vibes"
    with open(cp, "w") as f:
        json.dump(doc, f)
    r = _wf_doctor("--check", cal_bundle)
    assert r.returncode == 1
    assert "provenance" in r.stderr


def test_wf_doctor_accepts_pre_calibration_bundle(cal_bundle):
    # a bundle written before this plane existed: no calibration.json,
    # no roofline.json, no manifest entries — it must still validate
    mp = os.path.join(cal_bundle, "manifest.json")
    with open(mp) as f:
        manifest = json.load(f)
    manifest["files"] = [n for n in manifest["files"]
                         if n not in ("calibration.json",
                                      "roofline.json")]
    with open(mp, "w") as f:
        json.dump(manifest, f)
    os.remove(os.path.join(cal_bundle, "calibration.json"))
    os.remove(os.path.join(cal_bundle, "roofline.json"))
    r = _wf_doctor("--check", cal_bundle)
    assert r.returncode == 0, r.stderr


# ---------------------------------------------------------------------------
# wf_calibrate --check: the CI gate's exit-code contract
# ---------------------------------------------------------------------------

def _wf_calibrate(*args, env_extra=None):
    env = dict(os.environ)
    env.pop("WF_TPU_CALIBRATION", None)
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_calibrate.py"),
         *args], capture_output=True, text=True, timeout=60, env=env)


def test_wf_calibrate_check_exit_codes(tmp_path):
    fresh = tmp_path / "fresh.json"
    fresh.write_text(json.dumps(_store_doc()))
    r = _wf_calibrate("--check", str(fresh))
    assert r.returncode == 0, r.stderr + r.stdout
    assert "OK" in r.stdout

    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps(
        _store_doc(recorded_at=time.time() - cal.TTL_S - 86400)))
    r = _wf_calibrate("--check", str(stale))
    assert r.returncode == 1
    assert "days old" in r.stderr

    corrupt = tmp_path / "corrupt.json"
    corrupt.write_text("{broken")
    r = _wf_calibrate("--check", str(corrupt))
    assert r.returncode == 1

    r = _wf_calibrate("--check", str(tmp_path / "missing.json"))
    assert r.returncode == 1

    r = _wf_calibrate("--check", str(fresh),
                      env_extra={"WF_TPU_CALIBRATION": "0"})
    assert r.returncode == 2
    assert "kill switch" in r.stderr


def test_wf_calibrate_check_is_jax_free(tmp_path):
    """--check must run on scrape/CI hosts with no jax: poison the
    import and make sure the gate still answers."""
    fresh = tmp_path / "fresh.json"
    fresh.write_text(json.dumps(_store_doc()))
    poison = tmp_path / "jax.py"
    poison.write_text("raise ImportError('no jax on this host')\n")
    r = _wf_calibrate("--check", str(fresh), env_extra={
        "PYTHONPATH": str(tmp_path)})
    assert r.returncode == 0, r.stderr + r.stdout


# ---------------------------------------------------------------------------
# off path: roofline_plane=False builds nothing; the residue is one
# `is not None` check per call site (micro-asserted)
# ---------------------------------------------------------------------------

def test_off_path_never_builds():
    g, fired = _graph(_cfg(roofline_plane=False), name="cal_off_app")
    _drive(g)
    assert fired
    assert g._roofline is None
    assert g.stats()["Roofline"] == {"enabled": False}
    if g._health is not None:
        assert g._health.roofline is None
    # off-path budget (the tenant/latency plane stance): with every
    # cadence plane off, health_tick is a handful of attribute checks
    g2, _ = _graph(_cfg(roofline_plane=False, health_watchdog=False,
                        flight_recorder=False), name="cal_off2_app")
    _drive(g2)
    t0 = time.perf_counter()
    for _ in range(10_000):
        g2.health_tick()
    per_call = (time.perf_counter() - t0) / 10_000
    assert per_call < 5e-6, \
        f"disabled health_tick costs {per_call * 1e6:.2f}us/call"


def test_config_calibration_installs_store(tmp_path):
    path = tmp_path / "cal.json"
    path.write_text(json.dumps(_store_doc()))
    g, fired = _graph(_cfg(calibration=str(path)), n=512,
                      name="cal_cfg_app")
    _drive(g)
    assert fired
    store = cal.default_store()
    assert store is not None and store.path == str(path)
    sec = g.stats()["Roofline"]
    assert cal.is_calibrated(sec["bandwidth_provenance"])
    assert sec["bandwidth_bytes_per_sec"] == 5e9
