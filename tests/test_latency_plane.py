"""Latency plane (monitoring/latency_ledger.py): segment-sum honesty,
the SLO enter/latch/clear state machine, megastep shared_k accounting,
and the off-path micro-assert.

The honesty property is the plane's contract: the five critical-path
segments are a running-max boundary walk over each sampled trace's span
events, so their per-graph totals MUST telescope to the end-to-end
histogram's sum exactly — at every megastep K, with and without
map/filter fusion, with and without wire compression.  A decomposition
that does not sum to the whole is attributing latency that never
happened (or hiding latency that did), and the adaptive sizer
(analysis/latency.py) would plan against fiction.
"""

import dataclasses
import warnings

import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.monitoring.latency_ledger import SEGMENTS, LatencyLedger

N = 4096
CAP = 256
KEYS = 8


# ---------------------------------------------------------------------------
# harness: the packed-frames source (the megastep-eligible edge shape,
# same staging as tests/test_megastep.py) feeding map -> filter -> window
# ---------------------------------------------------------------------------

def _frames_blob(n, nkeys=KEYS, seed=11):
    rng = np.random.default_rng(seed)
    rec = np.zeros(n, dtype=[("k", "<i8"), ("ts", "<i8"), ("v", "<f8")])
    rec["k"] = rng.integers(0, nkeys, n)
    rec["ts"] = np.arange(n, dtype=np.int64) * 500
    rec["v"] = rng.random(n)
    return rec.tobytes()


def _source(n=N, cap=CAP):
    blob = _frames_blob(n)
    step = cap * 24

    def chunks():
        for i in range(0, len(blob), step):
            yield blob[i:i + step]

    from windflow_tpu.io.frames import FrameSource
    return FrameSource(chunks, nv=1, fields=["v"], output_batch_size=cap)


def _traced_cfg(**kw):
    kw.setdefault("flight_recorder", True)
    kw.setdefault("trace_sample_every", 2)
    kw.setdefault("latency_ledger", True)
    kw.setdefault("key_compaction", False)
    return dataclasses.replace(wf.default_config, **kw)


def _graph(cfg, n=N, cap=CAP, fused=True, name="lat_app"):
    fired = []
    m = (wf.MapTPU_Builder(lambda t: {"key": t["key"], "v": t["v"] * 2.0})
         .withName("m").build())
    f = (wf.FilterTPU_Builder(lambda t: (t["key"] & 7) != 7)
         .withName("f").build())
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"], lambda a, b: a + b)
         .withCBWindows(64, 32).withKeyBy(lambda t: t["key"])
         .withMaxKeys(KEYS).withName("win").build())
    snk = (wf.Sink_Builder(lambda r: fired.append(r) if r is not None
                           else None).withName("snk").build())
    g = wf.PipeGraph(name, config=cfg, time_policy=wf.TimePolicy.EVENT)
    pipe = g.add_source(_source(n, cap))
    pipe.add(m)
    if fused:
        pipe.chain(f)
    else:
        pipe.add(f)
    pipe.add(w).add_sink(snk)
    return g, fired


def _run(cfg, **kw):
    g, fired = _graph(cfg, **kw)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        g.run()
    return g, fired


# ---------------------------------------------------------------------------
# segment-sum honesty: the five segments telescope to the e2e span,
# exactly, at K=1/4/8 x fused/unfused x wire on/off
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("wire", [False, True],
                         ids=["wire_off", "wire_on"])
@pytest.mark.parametrize("fused", [True, False],
                         ids=["fused", "unfused"])
@pytest.mark.parametrize("k", [1, 4, 8])
def test_segment_sum_honesty(k, fused, wire):
    cfg = _traced_cfg(megastep_sweeps=k, wire_compression=wire)
    g, fired = _run(cfg, fused=fused)
    assert fired, "empty output proves nothing"
    lp = g.stats()["Latency_plane"]
    assert lp["enabled"]
    assert lp["traces_decomposed"] > 0
    assert lp["traces_dropped"] == 0
    assert lp["events_lost"] == 0
    # every trace is fully accounted: segment totals sum to the e2e
    # histogram sum (the boundary walk telescopes by construction)
    seg_sum = sum(lp["segments_total_usec"].values())
    e2e_sum = lp["e2e_usec"]["sum"]
    assert seg_sum == pytest.approx(e2e_sum, rel=1e-9, abs=0.5), \
        (k, fused, wire, lp["segments_total_usec"], lp["e2e_usec"])
    assert set(lp["segments_total_usec"]) == set(SEGMENTS)
    # per-op totals are the same decomposition grouped the other way
    per_op_sum = sum(e["total_usec"] for e in lp["per_op"].values())
    assert per_op_sum == pytest.approx(seg_sum, rel=1e-6, abs=0.5)
    shares = [e["budget_share"] for e in lp["per_op"].values()]
    assert all(0.0 <= s <= 1.0 for s in shares)
    assert sum(shares) == pytest.approx(1.0, abs=0.01)


# ---------------------------------------------------------------------------
# megastep accounting: shared_k traces, per-edge K, freshness floor
# ---------------------------------------------------------------------------

def test_megastep_shared_k_and_floor():
    cfg = _traced_cfg(megastep_sweeps=4, trace_sample_every=1)
    g, _ = _run(cfg, name="lat_ms_app")
    st = g.stats()
    edge = st["Megastep"]["edges"][0]
    assert edge["megasteps"] > 0, "megastep never assembled"
    lp = st["Latency_plane"]
    win = lp["per_op"]["win"]
    # traces that drained through a K-group carry shared_k: full wall
    # value in the histogram, 1/K credit in device_busy_usec
    assert win["shared_k_traces"] > 0
    assert win["megastep_k"] == 4
    assert win["freshness_floor_usec"] is None \
        or win["freshness_floor_usec"] >= 0
    dev = (win["segments_usec"].get("dispatched_to_device_done")
           or {}).get("sum", 0.0)
    assert win["device_busy_usec"] <= dev + 0.5


def test_freshness_gauge_populates():
    cfg = _traced_cfg(trace_sample_every=1)
    g, _ = _run(cfg, name="lat_fresh_app")
    win = g.stats()["Latency_plane"]["per_op"]["win"]
    fresh = win.get("freshness_usec")
    assert fresh is not None and fresh["count"] > 0


# ---------------------------------------------------------------------------
# SLO state machine: enter is immediate, the verdict latches, clear
# needs clear_after consecutive in-budget evaluations
# ---------------------------------------------------------------------------

class _NoRings:
    rings = ()


def _feed(led, e2e_usec, n, op="win", seg="emitted_to_dispatched"):
    for _ in range(n):
        led._recent.append((float(e2e_usec), [(op, seg, float(e2e_usec))]))


def test_slo_enter_latch_clear():
    led = LatencyLedger(_NoRings(), slo_ms=1.0, window=64,
                        clear_after=3, min_samples=8)
    # under min_samples: no evaluation at all
    _feed(led, 5000.0, 4)
    led.tick()
    assert not led.slo_active and led.verdict is None
    # enter: immediate once the window holds min_samples over budget
    _feed(led, 5000.0, 4)
    led.tick()
    assert led.slo_active and led.slo_entered == 1
    v = led.verdict
    assert v["state"] == "SLO_VIOLATED"
    assert v["dominant_op"] == "win"
    assert v["dominant_segment"] == "emitted_to_dispatched"
    assert "emitted→dispatched" in v["message"]
    assert v["budget_ms"] == 1.0
    # latch: still over, entered does not re-count
    led.tick()
    assert led.slo_active and led.slo_entered == 1
    # rotate the window to in-budget traces: one or two OK evaluations
    # must NOT clear (hysteresis), the third does
    led._recent.clear()
    _feed(led, 100.0, 16, seg="collected_to_sunk")
    led.tick()
    assert led.slo_active, "cleared after 1 OK tick"
    led.tick()
    assert led.slo_active, "cleared after 2 OK ticks"
    led.tick()
    assert not led.slo_active and led.slo_cleared == 1
    assert led.verdict is None
    assert led.last_verdict is not None  # forensics survive the clear
    # re-enter counts a fresh violation
    led._recent.clear()
    _feed(led, 9000.0, 8)
    led.tick()
    assert led.slo_active and led.slo_entered == 2


def test_slo_verdict_surfaces_in_health():
    # a sub-microsecond budget every real run violates instantly
    cfg = _traced_cfg(trace_sample_every=1, latency_slo_ms=0.001)
    g, _ = _graph(cfg, name="lat_slo_app")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        g.start()
        while not g.is_done():
            if not g.step():
                break
            g.health_tick()
        g.wait_end()
        g.health_tick()
    st = g.stats()
    slo = st["Latency_plane"]["slo"]
    assert slo["active"] and slo["entered"] >= 1
    v = slo["verdict"]
    assert v is not None and v["state"] == "SLO_VIOLATED"
    assert v["dominant_op"] in st["Latency_plane"]["per_op"]
    assert v["dominant_segment"] in SEGMENTS
    # the health plane carries the verdict on the dominant op ONLY —
    # one slow op does not paint the whole graph red
    h = st["Health"]
    assert h["graph_state"] == "SLO_VIOLATED"
    for name, hv in h["verdicts"].items():
        if name == v["dominant_op"]:
            assert hv["state"] == "SLO_VIOLATED"
            assert hv["slo"]["message"] == v["message"]
        else:
            assert hv["state"] != "SLO_VIOLATED"
            assert "slo" not in hv


# ---------------------------------------------------------------------------
# off path: latency_ledger=False (or no recorder) means the plane is
# never built — one `is not None` check is the whole cost
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("cfg_kw", [
    {"latency_ledger": False},
    {"flight_recorder": False},
], ids=["ledger_off", "recorder_off"])
def test_off_path_never_builds(cfg_kw):
    cfg = _traced_cfg(**cfg_kw)
    g, fired = _run(cfg, name="lat_off_app")
    assert fired
    assert g._latency is None
    assert all(getattr(rep, "latency", None) is None
               for rep in g._all_replicas)
    assert g.stats()["Latency_plane"] == {"enabled": False}
