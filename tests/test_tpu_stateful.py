"""Stateful keyed TPU operators (reference stateful Map_GPU/Filter_GPU,
``map_gpu.hpp:78-102`` / ``filter_gpu.hpp:119``): per-key device state,
in-order application within a key, state shared across replicas."""

import random

import pytest

import windflow_tpu as wf


def stream(n_keys, length):
    return [{"key": i % n_keys, "value": float(i % 13 + 1)}
            for i in range(length)]


@pytest.mark.parametrize("par", [1, 2, 3])
def test_stateful_map_running_sum_exact(par):
    """Every emitted value is the exact per-key running sum — at any
    parallelism: keyed staging partitions keys over replicas, so each key's
    tuples hit the shared state table in arrival order."""
    got = []
    length, n_keys, batch = 520, 6, 64
    src = (wf.Source_Builder(lambda: iter(stream(n_keys, length)))
           .withOutputBatchSize(batch).build())
    m = (wf.MapTPU_Builder(
            lambda t, s: ({"key": t["key"], "value": s + t["value"]},
                          s + t["value"]))
         .withKeyBy(lambda t: t["key"]).withInitialState(0.0)
         .withParallelism(par)
         .withNumKeySlots(64).build())
    snk = wf.Sink_Builder(
        lambda t: got.append((t["key"], t["value"])) if t else None).build()
    g = wf.PipeGraph("stateful_map", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(m).add_sink(snk)
    g.run()

    run_sums = {}
    expected = []
    for t in stream(n_keys, length):
        run_sums[t["key"]] = run_sums.get(t["key"], 0.0) + t["value"]
        expected.append((t["key"], run_sums[t["key"]]))
    assert sorted(got) == sorted(expected)
    # in-order within each key: emitted running sums strictly increase
    seen = {}
    for k, v in got:
        assert v > seen.get(k, 0.0)
        seen[k] = v


@pytest.mark.slow   # parallelism x batch soak (~6s): nightly leg (calibration-round headroom pass)
def test_stateful_map_metamorphic_totals():
    """Varying parallelism/batch size must reproduce identical per-key final
    totals (positive values: max running sum == total)."""
    rnd = random.Random(5)
    reference = None
    for run in range(4):
        par = rnd.randint(1, 3)
        batch = rnd.choice([16, 32, 128])
        maxes = {}
        src = (wf.Source_Builder(lambda: iter(stream(5, 600)))
               .withOutputBatchSize(batch).build())
        m = (wf.MapTPU_Builder(
                lambda t, s: ({"key": t["key"], "value": s + t["value"]},
                              s + t["value"]))
             .withKeyBy(lambda t: t["key"]).withInitialState(0.0)
             .withParallelism(par).build())
        snk = wf.Sink_Builder(
            lambda t: maxes.__setitem__(
                t["key"], max(maxes.get(t["key"], 0.0), t["value"]))
            if t else None).build()
        g = wf.PipeGraph("stateful_meta", wf.ExecutionMode.DEFAULT)
        g.add_source(src).add(m).add_sink(snk)
        g.run()
        if reference is None:
            reference = maxes
        else:
            assert maxes == reference, f"run {run} par={par} batch={batch}"
    totals = {}
    for t in stream(5, 600):
        totals[t["key"]] = totals.get(t["key"], 0.0) + t["value"]
    assert reference == totals


# par=1 (serial) vs par=2 (parallel replicas) are the two distinct
# ordering behaviors; the par=3 cell (~5s) rides the nightly leg
@pytest.mark.parametrize("par", [1, 2,
                                 pytest.param(3, marks=pytest.mark.slow)])
def test_stateful_filter_first_n_per_key(par):
    """Keep only the first 3 tuples of each key — a pure state-dependent,
    order-sensitive predicate; state updates must apply even for dropped
    tuples, and parallel replicas must see each key's tuples in order."""
    got = []
    n_keys = 9

    def pred(t, s):
        return s < 3, s + 1

    src = (wf.Source_Builder(lambda: iter(stream(n_keys, 400)))
           .withOutputBatchSize(50).build())
    f = (wf.FilterTPU_Builder(pred)
         .withKeyBy(lambda t: t["key"]).withInitialState(0)
         .withParallelism(par)
         .build())
    snk = wf.Sink_Builder(
        lambda t: got.append((t["key"], t["value"])) if t else None).build()
    g = wf.PipeGraph("stateful_filter", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(f).add_sink(snk)
    g.run()

    counts = {}
    expected = []
    for t in stream(n_keys, 400):
        c = counts.get(t["key"], 0)
        if c < 3:
            expected.append((t["key"], t["value"]))
        counts[t["key"]] = c + 1
    assert sorted(got) == sorted(expected)


def test_stateful_requires_keyby():
    with pytest.raises(wf.WindFlowError):
        wf.MapTPU_Builder(lambda t, s: (t, s)).withInitialState(0.0).build()


def test_stateful_key_slot_overflow():
    src = (wf.Source_Builder(lambda: iter(stream(100, 200)))
           .withOutputBatchSize(32).build())
    m = (wf.MapTPU_Builder(lambda t, s: (t, s))
         .withKeyBy(lambda t: t["key"]).withInitialState(0.0)
         .withNumKeySlots(8).build())
    snk = wf.Sink_Builder(lambda t: None).build()
    g = wf.PipeGraph("overflow", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(m).add_sink(snk)
    with pytest.raises(wf.WindFlowError, match="num_key_slots"):
        g.run()


def test_stateful_columnar_constant_key_parallel():
    """Regression: a scalar-returning key extractor on the columnar staging
    path must not drop rows — the vectorized partition only applies when the
    extractor returns a per-row array."""
    import struct
    from windflow_tpu.io import FrameSource

    n = 300
    recs = [(i % 5, 1_000 + i, float(i % 9 + 1)) for i in range(n)]
    blob = b"".join(struct.pack("<qqd", *r) for r in recs)

    def chunks():
        for lo in range(0, len(blob), 997):
            yield blob[lo:lo + 997]

    got = []
    src = FrameSource(chunks, nv=1, fmt="frames", output_batch_size=64)
    m = (wf.MapTPU_Builder(
            lambda t, s: ({"key": t["key"], "v0": s + t["v0"]}, s + t["v0"]))
         .withKeyBy(lambda t: 0).withInitialState(0.0)
         .withParallelism(2).build())
    snk = wf.Sink_Builder(
        lambda t: got.append(t["v0"]) if t is not None else None).build()
    g = wf.PipeGraph("const_key", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    g.add_source(src).add(m).add_sink(snk)
    g.run()

    run_sum, expected = 0.0, []
    for _, _, v in recs:
        run_sum += v
        expected.append(run_sum)
    assert sorted(got) == sorted(expected)


def test_stateful_int32_key_collision_routes_together():
    """Keys equal mod 2^32 are one logical key on device (int32 key space);
    host routing must send them to the same replica or per-key order breaks."""
    items = [{"key": (5 if i % 2 == 0 else 2**32 + 5), "value": 1.0}
             for i in range(120)]
    got = []
    src = (wf.Source_Builder(lambda: iter(items))
           .withOutputBatchSize(16).build())
    m = (wf.MapTPU_Builder(
            lambda t, s: ({"key": t["key"], "value": s + t["value"]},
                          s + t["value"]))
         .withKeyBy(lambda t: t["key"]).withInitialState(0.0)
         .withParallelism(3).build())
    snk = wf.Sink_Builder(
        lambda t: got.append(t["value"]) if t is not None else None).build()
    g = wf.PipeGraph("collide", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(m).add_sink(snk)
    g.run()
    # one logical key: running sums are exactly 1..120
    assert sorted(got) == [float(i) for i in range(1, 121)]


def test_chained_keyed_tpu_ops_with_different_extractors():
    """Regression: a key lane attached for one operator's extractor must not
    leak to a downstream operator keyed on a different field."""
    items = [{"a": i % 3, "b": (i + 1) % 5, "value": 1.0}
             for i in range(200)]
    got = []
    src = (wf.Source_Builder(lambda: iter(items))
           .withOutputBatchSize(32).build())
    # m1 at parallelism 1: a single upstream path keeps global order, so the
    # exact oracle below is valid; m2 at parallelism 2 exercises the keyed
    # TPU→TPU split (the stale-key-lane regression target).
    m1 = (wf.MapTPU_Builder(
            lambda t, s: ({"a": t["a"], "b": t["b"], "value": s + 1.0},
                          s + 1.0))
          .withKeyBy(lambda t: t["a"]).withInitialState(0.0)
          .withName("by_a").build())
    m2 = (wf.MapTPU_Builder(
            lambda t, s: ({"a": t["a"], "b": t["b"], "value": t["value"],
                           "bcount": s + 1.0}, s + 1.0))
          .withKeyBy(lambda t: t["b"]).withInitialState(0.0)
          .withParallelism(2).withName("by_b").build())
    snk = wf.Sink_Builder(
        lambda t: got.append((t["a"], t["b"], t["value"], t["bcount"]))
        if t is not None else None).build()
    g = wf.PipeGraph("two_keys", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(m1).add(m2).add_sink(snk)
    g.run()

    a_counts, b_counts, expected = {}, {}, []
    for t in items:
        a_counts[t["a"]] = a_counts.get(t["a"], 0.0) + 1.0
        b_counts[t["b"]] = b_counts.get(t["b"], 0.0) + 1.0
        expected.append((t["a"], t["b"], a_counts[t["a"]], b_counts[t["b"]]))
    assert sorted(got) == sorted(expected)


def test_stateful_then_stateless_device_edge():
    """TPU→TPU edge: stateful map feeds a stateless device filter without
    leaving HBM."""
    got = []
    src = (wf.Source_Builder(lambda: iter(stream(4, 256)))
           .withOutputBatchSize(64).build())
    m = (wf.MapTPU_Builder(
            lambda t, s: ({"key": t["key"], "value": s + t["value"]},
                          s + t["value"]))
         .withKeyBy(lambda t: t["key"]).withInitialState(0.0).build())
    f = wf.FilterTPU_Builder(lambda t: t["value"] > 100.0).build()
    snk = wf.Sink_Builder(
        lambda t: got.append(t["value"]) if t else None).build()
    g = wf.PipeGraph("stateful_edge", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(m).add(f).add_sink(snk)
    g.run()

    run_sums = {}
    expected = []
    for t in stream(4, 256):
        run_sums[t["key"]] = run_sums.get(t["key"], 0.0) + t["value"]
        if run_sums[t["key"]] > 100.0:
            expected.append(run_sums[t["key"]])
    assert sorted(got) == sorted(expected)


def test_keyed_routing_negative_and_wide_keys():
    """Negative and >2^31 keys end-to-end through keyed staging routing +
    state interning at parallelism > 1 (VERDICT r2 weak #10): routing must
    collapse exactly the keys the int32 device state collapses, so key K
    and K + 2^32 land on the same replica and the same state slot."""
    import jax.numpy as jnp
    raw = [-5, -1, 3, (1 << 32) + 3, (1 << 31) + 7, 7 - (1 << 31)]
    items = [{"key": raw[i % len(raw)], "value": 1} for i in range(240)]

    acc = {}
    src = (wf.Source_Builder(lambda: iter(items))
           .withOutputBatchSize(24).build())
    op = (wf.MapTPU_Builder(
            lambda t, s: ({"key": t["key"], "count": s + 1}, s + 1))
          .withInitialState(jnp.zeros((), jnp.int32))
          .withKeyBy(lambda t: t["key"]).withParallelism(3).build())
    snk = wf.Sink_Builder(
        lambda r: acc.__setitem__(int(r["key"]) & 0xFFFFFFFF,
                                  max(acc.get(int(r["key"]) & 0xFFFFFFFF, 0),
                                      int(r["count"])))
        if r is not None else None).build()
    g = wf.PipeGraph("widekeys", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(op).add_sink(snk)
    g.run()

    # int32 truncation collapses 3 and 2^32+3 into one key; (1<<31)+7 wraps
    # negative.  Per collapsed key, the final running count = #occurrences.
    exp = {}
    for t in items:
        k32 = t["key"] & 0xFFFFFFFF   # same u32 space the sink maps into
        exp[k32] = exp.get(k32, 0) + 1
    assert acc == exp
