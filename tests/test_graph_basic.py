"""End-to-end graph tests in the reference's metamorphic-oracle style
(``/root/reference/tests/graph_tests/test_graph_1.cpp``): randomized
parallelism/batch-size sweeps must reproduce run 0's sink accumulation
exactly, across DEFAULT and DETERMINISTIC modes."""

import random

import pytest

import windflow_tpu as wf


def make_stream(n_keys, length):
    # key/value records as dicts (arbitrary host tuples)
    return [{"key": i % n_keys, "value": i} for i in range(length)]


class Acc:
    def __init__(self):
        self.total = 0
        self.count = 0
        self.eos = 0

    def __call__(self, item, ctx=None):
        if item is None:
            self.eos += 1
        else:
            self.total += int(item["value"])
            self.count += 1


def run_linear(mode, length, n_keys, par, batch):
    acc = Acc()
    src = (wf.Source_Builder(lambda: iter(make_stream(n_keys, length)))
           .withName("src").withParallelism(1)
           .withOutputBatchSize(batch).build())
    mp = (wf.Map_Builder(lambda t: {"key": t["key"], "value": t["value"] * 2})
          .withName("map").withParallelism(par[0])
          .withOutputBatchSize(batch).build())
    flt = (wf.Filter_Builder(lambda t: t["value"] % 4 == 0)
           .withName("filter").withParallelism(par[1])
           .withOutputBatchSize(batch).build())
    snk = wf.Sink_Builder(acc).withName("sink").withParallelism(par[2]).build()
    g = wf.PipeGraph("test_linear", mode)
    g.add_source(src).add(mp).add(flt).add_sink(snk)
    g.run()
    return acc


# The metamorphic sweep covers DEFAULT and DETERMINISTIC, like the reference
# (test_graph_1.cpp:126,210); PROBABILISTIC is lossy by design and is tested
# via drop accounting below.
@pytest.mark.parametrize("mode", [wf.ExecutionMode.DEFAULT,
                                  wf.ExecutionMode.DETERMINISTIC])
def test_linear_metamorphic(mode):
    rnd = random.Random(42)
    length, n_keys = 1000, 7
    reference = None
    for run in range(6):
        par = [rnd.randint(1, 5) for _ in range(3)]
        batch = rnd.randint(1, 10)
        acc = run_linear(mode, length, n_keys, par, batch)
        assert acc.eos == par[2]  # one EOS callback per sink replica
        if reference is None:
            reference = (acc.total, acc.count)
        else:
            assert (acc.total, acc.count) == reference, \
                f"run {run} diverged with par={par} batch={batch}"
    # oracle sanity: filter keeps multiples of 4 after doubling
    expected = sum(v * 2 for v in range(length) if (v * 2) % 4 == 0)
    assert reference[0] == expected


def test_flatmap_keyby_reduce():
    """Source → FlatMap → keyed Reduce → Sink, sweeping parallelism."""
    length, n_keys = 600, 5
    reference = None
    rnd = random.Random(7)
    for run in range(5):
        par = rnd.randint(1, 4)
        batch = rnd.randint(1, 8)
        acc = Acc()
        last_states = {}

        def sink_fn(item, _last=last_states):
            if item is not None:
                _last[item["key"]] = item["value"]

        src = (wf.Source_Builder(lambda: iter(make_stream(n_keys, length)))
               .withOutputBatchSize(batch).build())
        fm = (wf.FlatMap_Builder(
                lambda t, shipper: [shipper.push(t), shipper.push(t)][0])
              .withParallelism(par).withOutputBatchSize(batch).build())
        red = (wf.Reduce_Builder(
                lambda t, s: {"key": t["key"],
                              "value": s["value"] + t["value"]},
                {"key": -1, "value": 0})
               .withKeyBy(lambda t: t["key"])
               .withParallelism(par).withOutputBatchSize(batch).build())
        snk = wf.Sink_Builder(sink_fn).build()
        g = wf.PipeGraph("fm_red", wf.ExecutionMode.DEFAULT)
        g.add_source(src).add(fm).add(red).add_sink(snk)
        g.run()
        result = tuple(sorted(last_states.items()))
        if reference is None:
            reference = result
        else:
            assert result == reference, f"run {run} diverged (par={par})"
    # each key's final rolling sum = 2x sum of its values (flatmap doubles)
    expected = {}
    for t in make_stream(n_keys, length):
        expected[t["key"]] = expected.get(t["key"], 0) + 2 * t["value"]
    assert dict(reference) == expected


def test_probabilistic_drops_counted():
    """Out-of-order EVENT-time stream through KSlack: dropped tuples are
    counted, survivors + drops add up to the input."""
    length = 500
    rnd = random.Random(3)
    items = [{"key": 0, "value": i,
              "ts": (i + rnd.randint(-40, 40)) * 1000}
             for i in range(length)]
    got = []
    src = (wf.Source_Builder(lambda: iter(items))
           .withTimestampExtractor(lambda t: max(0, t["ts"]))
           .withOutputBatchSize(4).build())
    mp = (wf.Map_Builder(lambda t: t).withParallelism(2)
          .withOutputBatchSize(4).build())
    snk = wf.Sink_Builder(
        lambda t: got.append(t["value"]) if t is not None else None).build()
    g = wf.PipeGraph("kslack", wf.ExecutionMode.PROBABILISTIC,
                     wf.TimePolicy.EVENT)
    g.add_source(src).add(mp).add_sink(snk)
    g.run()
    assert len(got) + g.get_num_dropped_tuples() == length
    assert len(got) > 0


def test_rebalancing_after_keyby():
    """REBALANCING routing (reference basic.hpp:87): round-robin even after
    a keyed stage, spreading a skewed key across replicas."""
    length = 400
    seen_replicas = set()

    def spy(t, ctx):
        seen_replicas.add(ctx.replica_index)
        return t

    src = (wf.Source_Builder(
        lambda: iter({"key": 0, "value": i} for i in range(length)))
        .withName("src").build())
    red = (wf.Reduce_Builder(lambda t, s: {**t, "n": s.get("n", 0) + 1}, dict)
           .withKeyBy(lambda t: t["key"]).withParallelism(3).build())
    reb = (wf.Map_Builder(spy).withName("rebalanced")
           .withParallelism(4).withRebalancing().build())
    acc = Acc()
    snk = wf.Sink_Builder(acc).build()
    g = wf.PipeGraph("rebalance", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(red).add(reb).add_sink(snk)
    g.run()
    assert acc.count == length
    # single hot key, but rebalancing spread work over every replica
    assert seen_replicas == {0, 1, 2, 3}


def test_rebalancing_conflicts_with_keyby():
    with pytest.raises(wf.WindFlowError):
        (wf.Map_Builder(lambda t: t).withKeyBy(lambda t: t)
         .withRebalancing()._routing())


def test_broadcast_routing():
    """withBroadcast (reference builders.hpp:252-1471): every replica of the
    operator receives every tuple."""
    length = 120
    per_replica = {}

    def spy(t, ctx):
        per_replica.setdefault(ctx.replica_index, []).append(t["value"])
        return t

    acc = Acc()
    src = (wf.Source_Builder(
        lambda: iter({"value": i} for i in range(length)))
        .withOutputBatchSize(8).build())
    bmap = (wf.Map_Builder(spy).withParallelism(3).withBroadcast().build())
    snk = wf.Sink_Builder(acc).build()
    g = wf.PipeGraph("bcast", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(bmap).add_sink(snk)
    g.run()
    assert set(per_replica) == {0, 1, 2}
    for vals in per_replica.values():
        assert sorted(vals) == list(range(length))
    # downstream sink sees every replica's copy
    assert acc.count == 3 * length


def test_broadcast_conflicts():
    with pytest.raises(wf.WindFlowError):
        (wf.Map_Builder(lambda t: t).withKeyBy(lambda t: 0)
         .withBroadcast()._routing())


def test_closing_function_runs_once_per_replica():
    """withClosingFunction (reference closing_func on every operator
    builder): runs at replica termination with the RuntimeContext."""
    closed = []
    acc = Acc()
    src = (wf.Source_Builder(lambda: iter({"value": i} for i in range(50)))
           .withOutputBatchSize(8).build())
    m = (wf.Map_Builder(lambda t: t).withParallelism(3)
         .withClosingFunction(lambda ctx: closed.append(
             (ctx.operator_name, ctx.replica_index))).build())
    snk = (wf.Sink_Builder(acc)
           .withClosingFunction(lambda: closed.append(("sink", 0))).build())
    g = wf.PipeGraph("closing", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(m).add_sink(snk)
    g.run()
    assert sorted(c for c in closed if c[0] != "sink") == \
        [("map", 0), ("map", 1), ("map", 2)]
    assert ("sink", 0) in closed
    assert acc.count == 50


def test_closing_function_on_chained_stages():
    """Both constituents' closers run when stages fuse into one replica."""
    closed = []
    acc = Acc()
    src = (wf.Source_Builder(lambda: iter({"value": i} for i in range(20)))
           .withOutputBatchSize(4).build())
    m1 = (wf.Map_Builder(lambda t: {"value": t["value"] + 1})
          .withClosingFunction(lambda: closed.append("m1")).build())
    m2 = (wf.Map_Builder(lambda t: {"value": t["value"] * 2})
          .withClosingFunction(lambda: closed.append("m2")).build())
    snk = wf.Sink_Builder(acc).build()
    g = wf.PipeGraph("closing_chain", wf.ExecutionMode.DEFAULT)
    mp = g.add_source(src)
    mp.add(m1)
    mp.chain(m2)
    mp.add_sink(snk)
    g.run()
    assert closed == ["m1", "m2"]
    assert acc.total == sum((i + 1) * 2 for i in range(20))


def test_start_wait_end_idiom():
    """The reference idiom g.start(); g.wait_end() works and matches
    run(); wait_end before start raises."""
    acc = Acc()
    src = (wf.Source_Builder(lambda: iter({"value": i} for i in range(40)))
           .withOutputBatchSize(8).build())
    snk = wf.Sink_Builder(acc).build()
    g = wf.PipeGraph("startwait", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add_sink(snk)
    g.start()
    g.wait_end()
    assert acc.count == 40
    assert g.getNumDroppedTuples() == 0

    g2 = wf.PipeGraph("nostart", wf.ExecutionMode.DEFAULT)
    with pytest.raises(wf.WindFlowError):
        g2.wait_end()


def test_merge_capacity_mismatch_into_ffat_tpu_raises_at_build():
    """Merged sources with different batch sizes relayed by capacity-
    preserving TPU stages into a fixed-capacity FfatWindowsTPU must fail
    at BUILD time with the offending sizes, not mid-run."""
    s1 = (wf.Source_Builder(lambda: iter({"k": 0, "v": i, "ts": i * 1000}
                                         for i in range(64)))
          .withTimestampExtractor(lambda t: t["ts"])
          .withOutputBatchSize(31).build())
    s2 = (wf.Source_Builder(lambda: iter({"k": 1, "v": i, "ts": i * 1000}
                                         for i in range(64)))
          .withTimestampExtractor(lambda t: t["ts"])
          .withOutputBatchSize(4).build())
    g = wf.PipeGraph("capmix", wf.ExecutionMode.DEFAULT, wf.TimePolicy.EVENT)
    p1 = g.add_source(s1)
    p2 = g.add_source(s2)
    merged = p1.merge(p2)
    merged.add(wf.MapTPU_Builder(lambda t: dict(t)).build())
    merged.add(wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                          lambda a, b: a + b)
               .withTBWindows(16_000, 4_000).withKeyBy(lambda t: t["k"])
               .withMaxKeys(2).build())
    merged.add_sink(wf.Sink_Builder(lambda r: None).build())
    with pytest.raises(wf.WindFlowError, match="fixed batch capacity"):
        g.run()


def _capmix_graph(op):
    """Two merged sources with unequal batch sizes relayed through a
    capacity-preserving TPU stage into ``op``."""
    s1 = (wf.Source_Builder(lambda: iter({"k": 0, "v": float(i)}
                                         for i in range(64)))
          .withOutputBatchSize(31).build())
    s2 = (wf.Source_Builder(lambda: iter({"k": 1, "v": float(i)}
                                         for i in range(64)))
          .withOutputBatchSize(4).build())
    g = wf.PipeGraph("capmix2", wf.ExecutionMode.DEFAULT)
    merged = g.add_source(s1).merge(g.add_source(s2))
    merged.add(wf.MapTPU_Builder(lambda t: dict(t)).build())
    merged.add(op)
    merged.add_sink(wf.Sink_Builder(lambda r: None).build())
    return g


def test_merge_capacity_mismatch_into_stateful_map_tpu_raises():
    op = (wf.MapTPU_Builder(
            lambda t, s: ({"k": t["k"], "v": t["v"] + s}, s + t["v"]))
          .withInitialState(0.0).withKeyBy(lambda t: t["k"]).build())
    with pytest.raises(wf.WindFlowError,
                       match=r"StatefulMapTPU.*\[4, 31\]"):
        _capmix_graph(op).run()


def test_merge_capacity_mismatch_into_stateful_filter_tpu_raises():
    op = (wf.FilterTPU_Builder(
            lambda t, s: (t["v"] > s, s + 1.0))
          .withInitialState(0.0).withKeyBy(lambda t: t["k"]).build())
    with pytest.raises(wf.WindFlowError,
                       match=r"StatefulFilterTPU.*\[4, 31\]"):
        _capmix_graph(op).run()


def test_merge_capacity_mismatch_into_dense_reduce_tpu_raises():
    op = (wf.ReduceTPU_Builder(
            lambda a, b: {"k": a["k"], "v": a["v"] + b["v"]})
          .withKeyBy(lambda t: t["k"]).withMaxKeys(2).build())
    with pytest.raises(wf.WindFlowError,
                       match=r"ReduceTPU\[withMaxKeys\].*\[4, 31\]"):
        _capmix_graph(op).run()


def test_merge_equal_capacity_into_dense_reduce_tpu_ok():
    """The generalized check only fires on UNEQUAL capacities."""
    s1 = (wf.Source_Builder(lambda: iter({"k": 0, "v": float(i)}
                                         for i in range(64)))
          .withOutputBatchSize(16).build())
    s2 = (wf.Source_Builder(lambda: iter({"k": 1, "v": float(i)}
                                         for i in range(64)))
          .withOutputBatchSize(16).build())
    got = []
    g = wf.PipeGraph("capok", wf.ExecutionMode.DEFAULT)
    merged = g.add_source(s1).merge(g.add_source(s2))
    merged.add(wf.ReduceTPU_Builder(
        lambda a, b: {"k": a["k"], "v": a["v"] + b["v"]})
        .withKeyBy(lambda t: t["k"]).withMaxKeys(2).build())
    merged.add_sink(wf.Sink_Builder(
        lambda r: got.append((int(r["k"]), float(r["v"])))
        if r is not None else None).build())
    g.run()
    per_key = {}
    for k, v in got:
        per_key[k] = per_key.get(k, 0.0) + v
    assert per_key == {0: float(sum(range(64))), 1: float(sum(range(64)))}
