"""Staging plane (windflow_tpu/staging): host-buffer recycling pool,
fused packed transfer, and driver-loop prefetch.

The reference gets its L1 data-plane rate from a lock-free batch
recycling pool (``recycling.hpp``) and async CUDA-stream staging
(``batch_gpu_t.hpp``); these tests pin the TPU reproduction's contracts:
steady-state staging reuses pooled buffers (zero numpy allocation),
the fused packed transfer round-trips exactly, prefetch lookahead never
reorders or duplicates data under backpressure, and a pool at capacity
degrades to plain allocation instead of blocking."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu import staging
from windflow_tpu.batch import WM_NONE, columns_to_device, stage_packed
from windflow_tpu.staging import PackedBatchBuilder, StagingPool


@pytest.fixture
def fresh_pool():
    """Swap in an isolated pool for the test (graph emitters bind the
    process-wide default pool at build time) and restore after."""
    pool = StagingPool()
    staging.set_default_pool(pool)
    yield pool
    staging.set_default_pool(None)


# ---------------------------------------------------------------------------
# pool mechanics
# ---------------------------------------------------------------------------

def test_pool_recycles_same_buffer():
    pool = StagingPool()
    a = pool.acquire(128)
    pool.release(a)
    b = pool.acquire(128)
    assert b is a                       # recycled, not reallocated
    assert pool.stats()["hits"] == 1 and pool.stats()["misses"] == 1


def test_pool_is_size_keyed():
    pool = StagingPool()
    a = pool.acquire(64)
    pool.release(a)
    c = pool.acquire(65)                # different size: fresh allocation
    assert c is not a and c.shape == (65,)
    assert pool.stats()["misses"] == 2


def test_pool_at_capacity_drops_instead_of_blocking():
    """Releases beyond the retention depth (or byte cap) are refused and
    counted — allocation pressure, never a deadlock."""
    pool = StagingPool(depth=2)
    bufs = [pool.acquire(32) for _ in range(5)]
    for b in bufs:
        pool.release(b)
    st = pool.stats()
    assert st["releases"] == 2 and st["drops_at_capacity"] == 3
    # acquire still works at capacity: two recycled, then fresh allocation
    out = [pool.acquire(32) for _ in range(3)]
    assert all(o.shape == (32,) for o in out)
    assert pool.stats()["hits"] == 2


def test_pool_byte_cap_refuses_retention():
    pool = StagingPool(depth=8, max_bytes=100)   # < one 32-word buffer
    b = pool.acquire(32)
    pool.release(b)
    assert pool.stats()["drops_at_capacity"] == 1
    assert pool.acquire(32) is not b             # nothing was retained


def test_pool_gate_blocks_until_device_done():
    """Re-acquiring a buffer whose gate is still in flight syncs on the
    gate (the recycling queue's blocking pop); a ready gate never syncs."""
    class Gate:
        def __init__(self):
            self.blocked = False

        def is_ready(self):
            return False

        def block_until_ready(self):
            self.blocked = True
            return self

    pool = StagingPool()
    buf = pool.acquire(16)
    gate = Gate()
    pool.release(buf, gate=gate)
    again = pool.acquire(16)
    assert again is buf
    assert gate.blocked and pool.stats()["gate_waits"] == 1

    # ready device gate: no wait counted
    buf2 = pool.acquire(16)
    arr = jnp.zeros(4)
    jax.block_until_ready(arr)
    pool.release(buf2, gate=arr)
    pool.acquire(16)
    assert pool.stats()["gate_waits"] == 1


# ---------------------------------------------------------------------------
# fused packed transfer
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [7, 32])   # partial and full fill
def test_packed_builder_round_trip(n):
    """PackedBatchBuilder + stage_packed must reproduce the lanes the
    direct (unfused) staging path produces: exact values for int32 /
    float32 / int64 (incl. negative) lanes, zero padding, prefix validity."""
    cap = 32
    cols = {
        "a": np.arange(n, dtype=np.int32) - 3,
        "b": np.linspace(-1.5, 2.5, n).astype(np.float32),
        "c": (np.arange(n, dtype=np.int64) * -(1 << 40)) + 5,
    }
    tss = np.arange(n, dtype=np.int64) * 1000 + 17
    leaves, treedef = jax.tree.flatten(cols)
    dtypes = tuple(str(l.dtype) for l in leaves)
    pool = StagingPool()
    b = PackedBatchBuilder(dtypes, cap, pool=pool)
    # stale recycled contents must not leak into padding: pre-poison
    b.buf[:] = 0xFFFFFFFF
    b.append(leaves, tss)
    db = stage_packed(b.finish(), treedef, dtypes, cap, n, watermark=123,
                      pool=pool)
    assert db.capacity == cap and db.size == n
    np.testing.assert_array_equal(np.asarray(db.valid),
                                  np.arange(cap) < n)
    np.testing.assert_array_equal(np.asarray(db.ts)[:n], tss)
    np.testing.assert_array_equal(np.asarray(db.ts)[n:], 0)
    for name in cols:
        lane = np.asarray(db.payload[name])
        np.testing.assert_array_equal(lane[:n], cols[name])
        np.testing.assert_array_equal(lane[n:], 0)


def test_packed_equals_unfused_columns_to_device(fresh_pool):
    """columns_to_device (now routed through the pooled packed path) must
    agree with a plain jnp.asarray staging of the same columns."""
    n, cap = 20, 32
    cols = {"k": np.arange(n, dtype=np.int32) % 5,
            "v": np.arange(n, dtype=np.float32) * 0.25}
    tss = np.arange(n, dtype=np.int64) * 10
    db = columns_to_device(dict(cols), tss, cap, watermark=7)
    for name in cols:
        np.testing.assert_array_equal(np.asarray(db.payload[name])[:n],
                                      cols[name])
    np.testing.assert_array_equal(np.asarray(db.ts)[:n], tss)
    assert db.ts_min == 0 and db.ts_max == (n - 1) * 10
    assert db.watermark == 7


def test_packed_builder_streams_across_appends():
    """Chunked appends land at their final packed offsets: three appends
    must produce the identical buffer as one."""
    cap = 24
    vals = np.arange(cap, dtype=np.float32)
    keys = np.arange(cap, dtype=np.int64) * 3 - 11
    tss = np.arange(cap, dtype=np.int64)
    pool = StagingPool()
    one = PackedBatchBuilder(("float32", "int64"), cap, pool=pool)
    one.append([vals, keys], tss)
    whole = one.finish().copy()
    three = PackedBatchBuilder(("float32", "int64"), cap, pool=pool)
    for lo, hi in ((0, 5), (5, 16), (16, 24)):
        three.append([vals[lo:hi], keys[lo:hi]], tss[lo:hi])
    np.testing.assert_array_equal(three.finish(), whole)


def test_builder_rejects_unpackable_dtypes():
    with pytest.raises(ValueError, match="unpackable"):
        PackedBatchBuilder(("float64",), 8, pool=StagingPool())


# ---------------------------------------------------------------------------
# steady-state reuse through a real graph
# ---------------------------------------------------------------------------

def _chained_graph(n_tuples, batch, config=None, got=None):
    got = got if got is not None else []
    # int payload: Python floats stack as float64, which is unpackable
    # (no cheap 64-bit device decode) and would bypass the pooled path
    src = (wf.Source_Builder(
            lambda: iter({"key": i % 8, "value": i}
                         for i in range(n_tuples)))
           .withOutputBatchSize(batch).build())
    m1 = wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "value": t["value"] * 2.0}).build()
    f1 = wf.FilterTPU_Builder(lambda t: t["value"] >= 0).build()
    m2 = wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "value": t["value"] + 1.0}).build()
    snk = wf.Sink_Builder(
        lambda r: got.append(float(r["value"])) if r is not None
        else None).build()
    g = wf.PipeGraph("staging_chain", wf.ExecutionMode.DEFAULT,
                     config=config)
    g.add_source(src).add(m1).add(f1).add(m2).add_sink(snk)
    return g, got


def test_steady_state_pool_hit_rate(fresh_pool):
    """Long chained-ops run: after warm-up the staging path must recycle
    buffers, not allocate — >= 90% pool hit rate (acceptance criterion),
    misses bounded by the pool warm-up, zero capacity drops."""
    g, got = _chained_graph(n_tuples=16384, batch=128)
    g.run()
    st = fresh_pool.stats()
    assert st["hits"] + st["misses"] >= 100     # the path actually ran
    assert st["hit_rate"] >= 0.90, st
    # warm-up misses only: bounded by pool depth + driver lookahead, not
    # proportional to the number of staged batches
    assert st["misses"] <= 8, st
    assert got and len(got) == 16384
    # the pool counters ride the monitoring stats dump
    top = g.stats()
    assert top["Staging_pool"]["hit_rate"] >= 0.90
    assert top["Stage_prefetch_depth"] == g.config.stage_prefetch_depth


def test_pool_survives_capacity_pressure_in_graph(fresh_pool):
    """A pool too small to retain anything must not deadlock or corrupt
    a run — staging falls back to allocation and the stream completes."""
    staging.set_default_pool(StagingPool(depth=1, max_bytes=1))
    g, got = _chained_graph(n_tuples=2048, batch=64)
    g.run()
    assert len(got) == 2048
    st = staging.default_pool().stats()
    assert st["hit_rate"] == 0.0 and st["drops_at_capacity"] > 0


# ---------------------------------------------------------------------------
# prefetch lookahead
# ---------------------------------------------------------------------------

def _prefetch_run(depth, n_tuples=4096, batch=64):
    cfg = wf.Config(stage_prefetch_depth=depth,
                    max_inflight_batches=2, max_inbox_messages=4)
    g, got = _chained_graph(n_tuples, batch, config=cfg)
    g.run()
    return got, g


def test_prefetch_ordering_under_backpressure(fresh_pool):
    """Lookahead packs batch N+1 while N's step runs; with tight
    in-transit caps forcing throttle cycles, the sink must still see
    every tuple exactly once, in order, for any prefetch depth."""
    expect, _ = _prefetch_run(0)
    assert len(expect) == 4096
    assert expect == sorted(expect)          # source order preserved
    for depth in (1, 3):
        got, g = _prefetch_run(depth)
        assert got == expect
        assert g.stats()["Stage_prefetch_ticks"] >= 0


def test_prefetch_respects_backpressure_caps(fresh_pool):
    """Prefetch passes re-check the in-transit caps: the high-water marks
    with lookahead enabled stay within one batch of the configured cap
    (lookahead must not overrun the throttle)."""
    _, g = _prefetch_run(3)
    cap = g.config.max_inbox_messages
    assert g.stats()["Max_inbox_depth_seen"] <= cap + 1


# ---------------------------------------------------------------------------
# multi-host staging metadata (ADVICE r5 medium)
# ---------------------------------------------------------------------------

def test_multihost_stage_attaches_no_ts_extrema(monkeypatch):
    """Multi-host `_stage_soa` computes ts extrema from the process-LOCAL
    tss slice; attaching them to the globally sharded batch let
    windows/ffat_tpu _regrow_for_span make divergent per-process ring
    growth decisions.  The sharded branch must attach None extrema (the
    SPMD-consistent eviction-cadence regrow is the growth path there)."""
    from windflow_tpu import batch as batch_mod
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("d",))
    sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        jax, "make_array_from_process_local_data",
        lambda sharding, a, gshape: jnp.asarray(a))
    db = batch_mod._stage_soa({"v": np.arange(8, dtype=np.int32)},
                              np.arange(8, dtype=np.int64) * 1000,
                              n=8, capacity=16, watermark=7_000, device=sh)
    assert db.ts_min is None and db.ts_max is None
    assert db.watermark == 7_000
