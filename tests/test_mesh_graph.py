"""Mesh execution through the public graph API (VERDICT r1 item 2): with
``Config.mesh`` set, staging emitters lay batches out data-sharded and
FfatWindowsTPU / ReduceTPU compile their sharded variants inside a normal
``PipeGraph.run()`` — the multi-chip path is no longer a standalone layer.
Runs on the virtual 8-device CPU mesh (conftest)."""

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import windflow_tpu as wf
from windflow_tpu.basic import Config
from windflow_tpu.parallel.mesh import KEY_AXIS, make_mesh

N_KEYS = 4
LENGTH = 384
WIN, SLIDE = 16, 4


def stream():
    return [{"key": i % N_KEYS, "value": i, "ts": i * 1000}
            for i in range(LENGTH)]


def oracle_cb():
    per_key = {}
    for t in stream():
        per_key.setdefault(t["key"], []).append(t["value"])
    count, total = 0, 0
    for vals in per_key.values():
        w = 0
        while w * SLIDE < len(vals):
            count += 1
            total += sum(vals[w * SLIDE: w * SLIDE + WIN])
            w += 1
    return count, total


def _mesh_cfg(data=2):
    return dataclasses.replace(Config(), mesh=make_mesh(8, data=data))


def test_ffat_tpu_cb_on_mesh():
    exp = oracle_cb()
    acc = {"count": 0, "total": 0}

    def on_result(r):
        if r is not None:
            acc["count"] += 1
            acc["total"] += int(r["value"])

    src = (wf.Source_Builder(lambda: iter(stream()))
           .withOutputBatchSize(64).build())
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                     lambda a, b: a + b)
          .withCBWindows(WIN, SLIDE)
          .withKeyBy(lambda t: t["key"])
          .withMaxKeys(N_KEYS).build())
    snk = wf.Sink_Builder(on_result).build()
    g = wf.PipeGraph("ffat_mesh", wf.ExecutionMode.DEFAULT,
                     config=_mesh_cfg())
    g.add_source(src).add(wf.MapTPU_Builder(lambda t: t).build()) \
        .add(op).add_sink(snk)
    g.run()

    assert (acc["count"], acc["total"]) == exp
    # the window state must actually live key-sharded on the mesh
    assert op._states[0]["cur"].sharding.spec == P(KEY_AXIS)


def test_ffat_tpu_tb_on_mesh():
    """Time-based FFAT windows through the mesh path (VERDICT r2 item 2):
    key-sharded pane rings with per-shard clocks, watermark frontier
    replicated, results exact vs the host oracle."""
    TWIN, TSLIDE = 16_000, 4_000
    per_key = {}
    for t in stream():
        per_key.setdefault(t["key"], []).append((t["ts"], t["value"]))
    exp = {}
    for k, pts in per_key.items():
        wids = set()
        for ts, _ in pts:
            last = ts // TSLIDE
            first = max(0, -(-(ts - TWIN + 1) // TSLIDE))
            wids.update(range(first, last + 1))
        for w in wids:
            vals = [v for ts, v in pts
                    if w * TSLIDE <= ts < w * TSLIDE + TWIN]
            if vals:
                exp[(k, w)] = sum(vals)

    got = {}
    src = (wf.Source_Builder(lambda: iter(stream()))
           .withTimestampExtractor(lambda t: t["ts"])
           .withOutputBatchSize(64).build())
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                     lambda a, b: a + b)
          .withTBWindows(TWIN, TSLIDE)
          .withKeyBy(lambda t: t["key"])
          .withMaxKeys(N_KEYS).build())
    snk = wf.Sink_Builder(
        lambda r: got.__setitem__((r["key"], r["wid"]), r["value"])
        if r is not None else None).build()
    g = wf.PipeGraph("ffat_mesh_tb", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT, config=_mesh_cfg())
    g.add_source(src).add(op).add_sink(snk)
    g.run()

    assert got == exp
    # pane rings and per-shard clocks must actually live key-sharded
    assert op._states[0]["cells"].sharding.spec == P(KEY_AXIS)
    assert op._states[0]["base"].sharding.spec == P(KEY_AXIS)
    st = op.dump_stats()
    assert st["Late_tuples_dropped"] == 0


def test_keyed_reduce_tpu_on_mesh_fold():
    """Generic (all_gather + fold) cross-chip combine: payload lanes keep
    their real values, so the record's key field survives."""
    acc = {}
    src = (wf.Source_Builder(lambda: iter(stream()))
           .withOutputBatchSize(64).build())
    op = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": b["key"], "value": a["value"] + b["value"],
                          "ts": b["ts"]})
          .withKeyBy(lambda t: t["key"]).withMaxKeys(N_KEYS).build())
    snk = wf.Sink_Builder(
        lambda r: acc.__setitem__(r["key"], acc.get(r["key"], 0)
                                  + int(r["value"]))
        if r is not None else None).build()
    g = wf.PipeGraph("red_mesh", config=_mesh_cfg())
    g.add_source(src).add(op).add_sink(snk)
    g.run()

    per_key = {}
    for t in stream():
        per_key[t["key"]] = per_key.get(t["key"], 0) + t["value"]
    assert acc == per_key


def test_keyed_reduce_tpu_on_mesh_pmax():
    """withMonoidCombiner("max"): the cross-chip combine rides ONE pmax
    collective.  Strictly negative values (a zero-identity bug would win
    every max) and a real key lane in the record — max(k, k) == k across
    chips, so the key survives the collective (unlike psum's
    all-leaves-summed contract)."""
    got = {}
    src = (wf.Source_Builder(
            lambda: iter({"key": i % N_KEYS, "value": -1.0 - (i % 97)}
                         for i in range(LENGTH)))
           .withOutputBatchSize(64).build())
    op = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": jnp.maximum(a["key"], b["key"]),
                          "value": jnp.maximum(a["value"], b["value"])})
          .withKeyBy(lambda t: t["key"]).withMaxKeys(N_KEYS)
          .withMonoidCombiner("max").build())
    snk = wf.Sink_Builder(
        lambda r: got.__setitem__(
            int(r["key"]), max(got.get(int(r["key"]), -1e30),
                               float(r["value"])))
        if r is not None else None).build()
    g = wf.PipeGraph("red_mesh_pmax", config=_mesh_cfg())
    g.add_source(src).add(op).add_sink(snk)
    g.run()

    per_key = {}
    for i in range(LENGTH):
        k, v = i % N_KEYS, -1.0 - (i % 97)
        per_key[k] = max(per_key.get(k, -1e30), v)
    assert got == per_key


def test_keyed_reduce_tpu_on_mesh_psum():
    """psum cross-chip combine: every payload lane must be zero-absorbing
    sum-like, so the key rides only the extractor (derived from the raw
    value lane, pre-combine); output rows arrive in dense key order.

    Pins the DATA-SHARDED ingest explicitly: a declared dense mesh
    reduce defaults to key-aligned ingest since the pallas round
    (mesh.mark_aligned_ingest), whose column-fill batching changes the
    per-batch record cadence this test counts — the aligned twin lives
    in tests/test_pallas_kernels.py."""
    got = []
    src = (wf.Source_Builder(lambda: iter({"value": i}
                                          for i in range(LENGTH)))
           .withOutputBatchSize(64).build())
    op = (wf.ReduceTPU_Builder(lambda a, b: {"value": a["value"] + b["value"]})
          .withKeyBy(lambda t: t["value"] % N_KEYS)
          .withMaxKeys(N_KEYS).withSumCombiner().build())
    snk = wf.Sink_Builder(
        lambda r: got.append(int(r["value"])) if r is not None else None) \
        .build()
    g = wf.PipeGraph("red_mesh_psum",
                     config=dataclasses.replace(
                         _mesh_cfg(), key_aligned_ingest=False))
    g.add_source(src).add(op).add_sink(snk)
    g.run()

    # every 64-tuple batch contains all 4 keys, so each batch yields exactly
    # 4 records compacted in dense-key order 0..3
    assert len(got) == (LENGTH // 64) * N_KEYS
    per_key = {k: 0 for k in range(N_KEYS)}
    for j, v in enumerate(got):
        per_key[j % N_KEYS] += v
    expect = {k: sum(i for i in range(LENGTH) if i % N_KEYS == k)
              for k in range(N_KEYS)}
    assert per_key == expect


def test_global_reduce_tpu_on_mesh():
    got = []
    src = (wf.Source_Builder(lambda: iter({"v": float(i)}
                                          for i in range(256)))
           .withOutputBatchSize(64).build())
    op = wf.ReduceTPU_Builder(lambda a, b: {"v": a["v"] + b["v"]}).build()
    snk = wf.Sink_Builder(
        lambda r: got.append(r["v"]) if r is not None else None).build()
    g = wf.PipeGraph("gred_mesh", config=_mesh_cfg(data=4))
    g.add_source(src).add(op).add_sink(snk)
    g.run()
    assert sum(got) == sum(range(256))
    assert len(got) == 4  # one combined record per staged batch


def test_mesh_requires_divisible_batch():
    import pytest
    cfg = _mesh_cfg()
    src = (wf.Source_Builder(lambda: iter(stream()))
           .withOutputBatchSize(60).build())  # 60 % 8 devices != 0
    g = wf.PipeGraph("bad", config=cfg)
    g.add_source(src) \
        .add(wf.MapTPU_Builder(lambda t: t).build()) \
        .add_sink(wf.Sink_Builder(lambda r: None).build())
    with pytest.raises(wf.WindFlowError, match="not divisible"):
        g.run()


def test_keyed_reduce_tpu_on_mesh_arbitrary_keys():
    """Keyed mesh Reduce WITHOUT withMaxKeys: keys from the full int32
    range (negative, huge) hash-shard to their owner chip over an
    all_to_all; nothing is dropped and per-key totals are exact
    (VERDICT r2 item 5; reference reduce_gpu.hpp:227-258)."""
    import numpy as np
    rnd = np.random.default_rng(9)
    raw_keys = rnd.integers(-2**31, 2**31, 37).astype(np.int64)
    items = [{"key": int(raw_keys[i % len(raw_keys)]), "value": i}
             for i in range(LENGTH)]

    acc = {}
    src = (wf.Source_Builder(lambda: iter(items))
           .withOutputBatchSize(64).build())
    op = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": b["key"], "value": a["value"] + b["value"]})
          .withKeyBy(lambda t: t["key"]).build())   # NO withMaxKeys
    snk = wf.Sink_Builder(
        lambda r: acc.__setitem__(int(r["key"]),
                                  acc.get(int(r["key"]), 0)
                                  + int(r["value"]))
        if r is not None else None).build()
    g = wf.PipeGraph("red_mesh_arb", config=_mesh_cfg())
    g.add_source(src).add(op).add_sink(snk)
    g.run()

    exp = {}
    for t in items:
        k = np.int32(t["key"] & 0xFFFFFFFF).item() \
            if t["key"] >= 2**31 else t["key"]
        exp[k] = exp.get(k, 0) + t["value"]
    assert acc == exp
    assert op.num_dropped_tuples() == 0


def test_mesh_arbitrary_keys_int32_max_not_dropped():
    """A genuine key of INT32_MAX must not be mistaken for the reduce's
    invalid-lane sentinel and silently dropped (the sort lane is int64 with
    an out-of-range sentinel)."""
    items = [{"key": 2**31 - 1, "value": i} for i in range(64)]
    acc = {}
    src = (wf.Source_Builder(lambda: iter(items))
           .withOutputBatchSize(64).build())
    op = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": b["key"], "value": a["value"] + b["value"]})
          .withKeyBy(lambda t: t["key"]).build())
    snk = wf.Sink_Builder(
        lambda r: acc.__setitem__(int(r["key"]),
                                  acc.get(int(r["key"]), 0)
                                  + int(r["value"]))
        if r is not None else None).build()
    g = wf.PipeGraph("red_mesh_maxkey", config=_mesh_cfg())
    g.add_source(src).add(op).add_sink(snk)
    g.run()
    assert acc == {2**31 - 1: sum(range(64))}
    assert op.num_dropped_tuples() == 0


def test_mesh_long_stream_soak():
    """Long-stream soak of the mesh path (hundreds of staged batches
    through the sharded FFAT step): state rolls far past the ring length,
    counters stay exact, nothing leaks or drifts."""
    n = 12_800                      # 200 staged batches of 64
    acc = {"count": 0, "total": 0}
    src = (wf.Source_Builder(
            lambda: iter({"key": i % N_KEYS, "value": i, "ts": i * 1000}
                         for i in range(n)))
           .withOutputBatchSize(64).build())
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                     lambda a, b: a + b)
          .withCBWindows(WIN, SLIDE).withKeyBy(lambda t: t["key"])
          .withMaxKeys(N_KEYS).build())
    snk = wf.Sink_Builder(
        lambda r: (acc.__setitem__("count", acc["count"] + 1),
                   acc.__setitem__("total", acc["total"] + int(r["value"])))
        if r is not None else None).build()
    g = wf.PipeGraph("mesh_soak", config=_mesh_cfg())
    g.add_source(src).add(op).add_sink(snk)
    g.run()

    per_key = {}
    for i in range(n):
        per_key.setdefault(i % N_KEYS, []).append(i)
    count = total = 0
    for vals in per_key.values():
        w = 0
        while w * SLIDE < len(vals):
            count += 1
            total += sum(vals[w * SLIDE: w * SLIDE + WIN])
            w += 1
    assert (acc["count"], acc["total"]) == (count, total)


def test_stateful_map_tpu_on_mesh_sharded_state():
    """Keyed stateful MapTPU on the mesh: the dense slot table is sharded
    along the key axis, lanes merge back with one psum, and per-key running
    sums stay exact across hundreds of batches."""
    import jax.numpy as jnp
    n = 1024
    acc = {}
    src = (wf.Source_Builder(lambda: iter({"key": i % 8, "value": float(i)}
                                          for i in range(n)))
           .withOutputBatchSize(64).build())
    sm = (wf.MapTPU_Builder(
            lambda t, s: ({"key": t["key"], "run": s + t["value"]},
                          s + t["value"]))
          .withInitialState(jnp.zeros((), jnp.float32))
          .withKeyBy(lambda t: t["key"]).withNumKeySlots(8)
          .withDenseKeys().build())
    snk = wf.Sink_Builder(
        lambda r: acc.__setitem__(int(r["key"]), float(r["run"]))
        if r is not None else None).build()
    g = wf.PipeGraph("mesh_stateful", config=_mesh_cfg())
    g.add_source(src).add(sm).add_sink(snk)
    g.run()
    exp = {k: sum(float(i) for i in range(n) if i % 8 == k)
           for k in range(8)}
    assert acc == exp
    assert sm._state.sharding.spec == P(KEY_AXIS)

    # interned (non-dense) variant with a filter
    kept = []
    src2 = (wf.Source_Builder(lambda: iter({"key": 100 + (i % 4),
                                            "value": i} for i in range(256)))
            .withOutputBatchSize(64).build())
    sf = (wf.FilterTPU_Builder(
            lambda t, s: ((s + 1) % 2 == 1, s + 1))   # keep every other
          .withInitialState(jnp.zeros((), jnp.int32))
          .withKeyBy(lambda t: t["key"]).withNumKeySlots(8).build())
    snk2 = wf.Sink_Builder(
        lambda r: kept.append(int(r["value"])) if r is not None else None) \
        .build()
    g2 = wf.PipeGraph("mesh_stateful_f", config=_mesh_cfg())
    g2.add_source(src2).add(sf).add_sink(snk2)
    g2.run()
    # per key, occurrences alternate keep/drop starting with keep
    exp2 = sorted(i for i in range(256) if (i // 4) % 2 == 0)
    assert sorted(kept) == exp2


def test_mesh_stateful_out_of_range_keys_dropped():
    """Dense keys outside [0, num_key_slots) must drop on the mesh exactly
    as on a single chip — no shard owns them, so no zeroed ghost records."""
    import jax.numpy as jnp
    got = []
    src = (wf.Source_Builder(
            lambda: iter({"key": (99 if i % 3 == 0 else i % 8),
                          "value": float(i)} for i in range(192)))
           .withOutputBatchSize(64).build())
    sm = (wf.MapTPU_Builder(
            lambda t, s: ({"key": t["key"], "run": s + t["value"]},
                          s + t["value"]))
          .withInitialState(jnp.zeros((), jnp.float32))
          .withKeyBy(lambda t: t["key"]).withNumKeySlots(8)
          .withDenseKeys().build())
    snk = wf.Sink_Builder(
        lambda r: got.append(int(r["key"])) if r is not None else None) \
        .build()
    g = wf.PipeGraph("mesh_oor", config=_mesh_cfg())
    g.add_source(src).add(sm).add_sink(snk)
    g.run()
    n_in_range = sum(1 for i in range(192) if i % 3 != 0)
    assert len(got) == n_in_range
    assert all(0 <= k < 8 for k in got)
