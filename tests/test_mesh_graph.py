"""Mesh execution through the public graph API (VERDICT r1 item 2): with
``Config.mesh`` set, staging emitters lay batches out data-sharded and
FfatWindowsTPU / ReduceTPU compile their sharded variants inside a normal
``PipeGraph.run()`` — the multi-chip path is no longer a standalone layer.
Runs on the virtual 8-device CPU mesh (conftest)."""

import dataclasses

import jax
from jax.sharding import PartitionSpec as P

import windflow_tpu as wf
from windflow_tpu.basic import Config
from windflow_tpu.parallel.mesh import KEY_AXIS, make_mesh

N_KEYS = 4
LENGTH = 384
WIN, SLIDE = 16, 4


def stream():
    return [{"key": i % N_KEYS, "value": i, "ts": i * 1000}
            for i in range(LENGTH)]


def oracle_cb():
    per_key = {}
    for t in stream():
        per_key.setdefault(t["key"], []).append(t["value"])
    count, total = 0, 0
    for vals in per_key.values():
        w = 0
        while w * SLIDE < len(vals):
            count += 1
            total += sum(vals[w * SLIDE: w * SLIDE + WIN])
            w += 1
    return count, total


def _mesh_cfg(data=2):
    return dataclasses.replace(Config(), mesh=make_mesh(8, data=data))


def test_ffat_tpu_cb_on_mesh():
    exp = oracle_cb()
    acc = {"count": 0, "total": 0}

    def on_result(r):
        if r is not None:
            acc["count"] += 1
            acc["total"] += int(r["value"])

    src = (wf.Source_Builder(lambda: iter(stream()))
           .withOutputBatchSize(64).build())
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                     lambda a, b: a + b)
          .withCBWindows(WIN, SLIDE)
          .withKeyBy(lambda t: t["key"])
          .withMaxKeys(N_KEYS).build())
    snk = wf.Sink_Builder(on_result).build()
    g = wf.PipeGraph("ffat_mesh", wf.ExecutionMode.DEFAULT,
                     config=_mesh_cfg())
    g.add_source(src).add(wf.MapTPU_Builder(lambda t: t).build()) \
        .add(op).add_sink(snk)
    g.run()

    assert (acc["count"], acc["total"]) == exp
    # the window state must actually live key-sharded on the mesh
    assert op._states[0]["cur"].sharding.spec == P(KEY_AXIS)


def test_ffat_tpu_tb_on_mesh():
    """Time-based FFAT windows through the mesh path (VERDICT r2 item 2):
    key-sharded pane rings with per-shard clocks, watermark frontier
    replicated, results exact vs the host oracle."""
    TWIN, TSLIDE = 16_000, 4_000
    per_key = {}
    for t in stream():
        per_key.setdefault(t["key"], []).append((t["ts"], t["value"]))
    exp = {}
    for k, pts in per_key.items():
        wids = set()
        for ts, _ in pts:
            last = ts // TSLIDE
            first = max(0, -(-(ts - TWIN + 1) // TSLIDE))
            wids.update(range(first, last + 1))
        for w in wids:
            vals = [v for ts, v in pts
                    if w * TSLIDE <= ts < w * TSLIDE + TWIN]
            if vals:
                exp[(k, w)] = sum(vals)

    got = {}
    src = (wf.Source_Builder(lambda: iter(stream()))
           .withTimestampExtractor(lambda t: t["ts"])
           .withOutputBatchSize(64).build())
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                     lambda a, b: a + b)
          .withTBWindows(TWIN, TSLIDE)
          .withKeyBy(lambda t: t["key"])
          .withMaxKeys(N_KEYS).build())
    snk = wf.Sink_Builder(
        lambda r: got.__setitem__((r["key"], r["wid"]), r["value"])
        if r is not None else None).build()
    g = wf.PipeGraph("ffat_mesh_tb", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT, config=_mesh_cfg())
    g.add_source(src).add(op).add_sink(snk)
    g.run()

    assert got == exp
    # pane rings and per-shard clocks must actually live key-sharded
    assert op._states[0]["cells"].sharding.spec == P(KEY_AXIS)
    assert op._states[0]["base"].sharding.spec == P(KEY_AXIS)
    st = op.dump_stats()
    assert st["Late_tuples_dropped"] == 0


def test_keyed_reduce_tpu_on_mesh_fold():
    """Generic (all_gather + fold) cross-chip combine: payload lanes keep
    their real values, so the record's key field survives."""
    acc = {}
    src = (wf.Source_Builder(lambda: iter(stream()))
           .withOutputBatchSize(64).build())
    op = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": b["key"], "value": a["value"] + b["value"],
                          "ts": b["ts"]})
          .withKeyBy(lambda t: t["key"]).withMaxKeys(N_KEYS).build())
    snk = wf.Sink_Builder(
        lambda r: acc.__setitem__(r["key"], acc.get(r["key"], 0)
                                  + int(r["value"]))
        if r is not None else None).build()
    g = wf.PipeGraph("red_mesh", config=_mesh_cfg())
    g.add_source(src).add(op).add_sink(snk)
    g.run()

    per_key = {}
    for t in stream():
        per_key[t["key"]] = per_key.get(t["key"], 0) + t["value"]
    assert acc == per_key


def test_keyed_reduce_tpu_on_mesh_psum():
    """psum cross-chip combine: every payload lane must be zero-absorbing
    sum-like, so the key rides only the extractor (derived from the raw
    value lane, pre-combine); output rows arrive in dense key order."""
    got = []
    src = (wf.Source_Builder(lambda: iter({"value": i}
                                          for i in range(LENGTH)))
           .withOutputBatchSize(64).build())
    op = (wf.ReduceTPU_Builder(lambda a, b: {"value": a["value"] + b["value"]})
          .withKeyBy(lambda t: t["value"] % N_KEYS)
          .withMaxKeys(N_KEYS).withSumCombiner().build())
    snk = wf.Sink_Builder(
        lambda r: got.append(int(r["value"])) if r is not None else None) \
        .build()
    g = wf.PipeGraph("red_mesh_psum", config=_mesh_cfg())
    g.add_source(src).add(op).add_sink(snk)
    g.run()

    # every 64-tuple batch contains all 4 keys, so each batch yields exactly
    # 4 records compacted in dense-key order 0..3
    assert len(got) == (LENGTH // 64) * N_KEYS
    per_key = {k: 0 for k in range(N_KEYS)}
    for j, v in enumerate(got):
        per_key[j % N_KEYS] += v
    expect = {k: sum(i for i in range(LENGTH) if i % N_KEYS == k)
              for k in range(N_KEYS)}
    assert per_key == expect


def test_global_reduce_tpu_on_mesh():
    got = []
    src = (wf.Source_Builder(lambda: iter({"v": float(i)}
                                          for i in range(256)))
           .withOutputBatchSize(64).build())
    op = wf.ReduceTPU_Builder(lambda a, b: {"v": a["v"] + b["v"]}).build()
    snk = wf.Sink_Builder(
        lambda r: got.append(r["v"]) if r is not None else None).build()
    g = wf.PipeGraph("gred_mesh", config=_mesh_cfg(data=4))
    g.add_source(src).add(op).add_sink(snk)
    g.run()
    assert sum(got) == sum(range(256))
    assert len(got) == 4  # one combined record per staged batch


def test_mesh_requires_divisible_batch():
    import pytest
    cfg = _mesh_cfg()
    src = (wf.Source_Builder(lambda: iter(stream()))
           .withOutputBatchSize(60).build())  # 60 % 8 devices != 0
    g = wf.PipeGraph("bad", config=cfg)
    g.add_source(src) \
        .add(wf.MapTPU_Builder(lambda t: t).build()) \
        .add_sink(wf.Sink_Builder(lambda r: None).build())
    with pytest.raises(wf.WindFlowError, match="not divisible"):
        g.run()


def test_keyed_reduce_tpu_on_mesh_arbitrary_keys():
    """Keyed mesh Reduce WITHOUT withMaxKeys: keys from the full int32
    range (negative, huge) hash-shard to their owner chip over an
    all_to_all; nothing is dropped and per-key totals are exact
    (VERDICT r2 item 5; reference reduce_gpu.hpp:227-258)."""
    import numpy as np
    rnd = np.random.default_rng(9)
    raw_keys = rnd.integers(-2**31, 2**31, 37).astype(np.int64)
    items = [{"key": int(raw_keys[i % len(raw_keys)]), "value": i}
             for i in range(LENGTH)]

    acc = {}
    src = (wf.Source_Builder(lambda: iter(items))
           .withOutputBatchSize(64).build())
    op = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": b["key"], "value": a["value"] + b["value"]})
          .withKeyBy(lambda t: t["key"]).build())   # NO withMaxKeys
    snk = wf.Sink_Builder(
        lambda r: acc.__setitem__(int(r["key"]),
                                  acc.get(int(r["key"]), 0)
                                  + int(r["value"]))
        if r is not None else None).build()
    g = wf.PipeGraph("red_mesh_arb", config=_mesh_cfg())
    g.add_source(src).add(op).add_sink(snk)
    g.run()

    exp = {}
    for t in items:
        k = np.int32(t["key"] & 0xFFFFFFFF).item() \
            if t["key"] >= 2**31 else t["key"]
        exp[k] = exp.get(k, 0) + t["value"]
    assert acc == exp
    assert op.num_dropped_tuples() == 0


def test_mesh_arbitrary_keys_int32_max_not_dropped():
    """A genuine key of INT32_MAX must not be mistaken for the reduce's
    invalid-lane sentinel and silently dropped (the sort lane is int64 with
    an out-of-range sentinel)."""
    items = [{"key": 2**31 - 1, "value": i} for i in range(64)]
    acc = {}
    src = (wf.Source_Builder(lambda: iter(items))
           .withOutputBatchSize(64).build())
    op = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": b["key"], "value": a["value"] + b["value"]})
          .withKeyBy(lambda t: t["key"]).build())
    snk = wf.Sink_Builder(
        lambda r: acc.__setitem__(int(r["key"]),
                                  acc.get(int(r["key"]), 0)
                                  + int(r["value"]))
        if r is not None else None).build()
    g = wf.PipeGraph("red_mesh_maxkey", config=_mesh_cfg())
    g.add_source(src).add(op).add_sink(snk)
    g.run()
    assert acc == {2**31 - 1: sum(range(64))}
    assert op.num_dropped_tuples() == 0


def test_mesh_long_stream_soak():
    """Long-stream soak of the mesh path (hundreds of staged batches
    through the sharded FFAT step): state rolls far past the ring length,
    counters stay exact, nothing leaks or drifts."""
    n = 12_800                      # 200 staged batches of 64
    acc = {"count": 0, "total": 0}
    src = (wf.Source_Builder(
            lambda: iter({"key": i % N_KEYS, "value": i, "ts": i * 1000}
                         for i in range(n)))
           .withOutputBatchSize(64).build())
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                     lambda a, b: a + b)
          .withCBWindows(WIN, SLIDE).withKeyBy(lambda t: t["key"])
          .withMaxKeys(N_KEYS).build())
    snk = wf.Sink_Builder(
        lambda r: (acc.__setitem__("count", acc["count"] + 1),
                   acc.__setitem__("total", acc["total"] + int(r["value"])))
        if r is not None else None).build()
    g = wf.PipeGraph("mesh_soak", config=_mesh_cfg())
    g.add_source(src).add(op).add_sink(snk)
    g.run()

    per_key = {}
    for i in range(n):
        per_key.setdefault(i % N_KEYS, []).append(i)
    count = total = 0
    for vals in per_key.values():
        w = 0
        while w * SLIDE < len(vals):
            count += 1
            total += sum(vals[w * SLIDE: w * SLIDE + WIN])
            w += 1
    assert (acc["count"], acc["total"]) == (count, total)
