"""DeviceSource: batches born on device (io/device_source.py) feeding
device operators with no host staging — INGRESS and EVENT policies, both
checked against pure-Python oracles through whole graphs."""

import jax.numpy as jnp
import numpy as np
import pytest

import windflow_tpu as wf

CAP, NB, K = 64, 6, 4


def batch_fn(i):
    """Batch i holds records (key = lane % K, v = i*CAP + lane)."""
    lane = jnp.arange(CAP, dtype=jnp.int32)
    return {"key": lane % K,
            "v": (i * CAP + lane).astype(jnp.float32)}


def oracle_windows(win, slide):
    per_key = {}
    for i in range(NB):
        for lane in range(CAP):
            per_key.setdefault(lane % K, []).append(float(i * CAP + lane))
    exp = {}
    for k, vals in per_key.items():
        w = 0
        while w * slide < len(vals):
            seg = vals[w * slide: w * slide + win]
            if seg:
                exp[(k, w)] = sum(seg)
            w += 1
    return exp


def test_device_source_ffat_ingress():
    got = {}
    src = (wf.DeviceSource_Builder(batch_fn)
           .withCapacity(CAP).withNumBatches(NB).build())
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"], lambda a, b: a + b)
         .withKeyBy(lambda t: t["key"]).withMaxKeys(K)
         .withCBWindows(16, 8).build())
    snk = wf.Sink_Builder(
        lambda r: got.__setitem__((r["key"], r["wid"]), r["value"])
        if r is not None else None).build()
    g = wf.PipeGraph("dev_src", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.INGRESS)
    g.add_source(src).add(w).add_sink(snk)
    g.run()
    assert got == oracle_windows(16, 8)


def test_device_source_event_time_tb():
    """EVENT policy: ts lane generated on device, watermark frontier from
    the host-side wm_fn — time windows fire mid-stream, not just at EOS."""
    got = {}
    usec = 1000

    def ts_fn(i):
        return (i * CAP + jnp.arange(CAP)) * usec

    def wm_fn(i):
        return (i * CAP + CAP - 1) * usec

    src = (wf.DeviceSource_Builder(batch_fn)
           .withCapacity(CAP).withNumBatches(NB)
           .withTimestampFn(ts_fn, wm_fn).build())
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"], lambda a, b: a + b)
         .withKeyBy(lambda t: t["key"]).withMaxKeys(K)
         .withTBWindows(32 * usec, 32 * usec).build())
    rows = []
    snk = wf.Sink_Builder(
        lambda r: rows.append(r) if r is not None else None).build()
    g = wf.PipeGraph("dev_src_tb", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    g.add_source(src).add(w).add_sink(snk)
    g.run()
    got = {(r["key"], r["wid"]): r["value"] for r in rows}
    # oracle: tumbling 32-tick windows over ts = global index
    per = {}
    for i in range(NB):
        for lane in range(CAP):
            g_idx = i * CAP + lane
            per.setdefault((lane % K, g_idx // 32), 0.0)
            per[(lane % K, g_idx // 32)] += float(g_idx)
    assert got == per


def test_device_source_chained_map():
    """DeviceSource feeds a fused device chain (no staging edge at all)."""
    acc = []
    src = (wf.DeviceSource_Builder(batch_fn)
           .withCapacity(CAP).withNumBatches(2).build())
    m = wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "v": t["v"] * 2.0}).build()
    snk = wf.Sink_Builder(
        lambda t: acc.append(t["v"]) if t is not None else None).build()
    g = wf.PipeGraph("dev_src_map", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.INGRESS)
    g.add_source(src).add(m).add_sink(snk)
    g.run()
    assert sorted(acc) == [2.0 * x for x in range(2 * CAP)]


def test_device_source_validation():
    with pytest.raises(wf.WindFlowError):
        wf.DeviceSource_Builder(batch_fn).withCapacity(0) \
            .withNumBatches(3).build()
    with pytest.raises(wf.WindFlowError):
        wf.DeviceSource_Builder(batch_fn).withCapacity(8) \
            .withNumBatches(3).withOutputBatchSize(8)
    # EVENT policy without ts_fn/wm_fn fails at start
    src = (wf.DeviceSource_Builder(batch_fn)
           .withCapacity(CAP).withNumBatches(1).build())
    g = wf.PipeGraph("dev_src_bad", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    g.add_source(src).add_sink(wf.Sink_Builder(lambda t: None).build())
    with pytest.raises(wf.WindFlowError, match="ts_fn"):
        g.run()
    # ...and ts_fn under INGRESS fails too: event-time lanes behind a
    # wall-clock watermark would silently drop everything as late
    src2 = (wf.DeviceSource_Builder(batch_fn)
            .withCapacity(CAP).withNumBatches(1)
            .withTimestampFn(lambda i: jnp.arange(CAP, dtype=jnp.int64),
                             lambda i: CAP).build())
    g2 = wf.PipeGraph("dev_src_bad2", wf.ExecutionMode.DEFAULT,
                      wf.TimePolicy.INGRESS)
    g2.add_source(src2).add_sink(wf.Sink_Builder(lambda t: None).build())
    with pytest.raises(wf.WindFlowError, match="EVENT"):
        g2.run()
