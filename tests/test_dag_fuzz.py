"""Randomized DAG fuzzer (the reference's metamorphic strategy taken past
its fixed topologies): each seed generates a random DAG — host and TPU
stages, optional split into two branches, optional second source merged in,
optional keyed window or reduce tail — and runs it under several random
parallelism/batch configurations.  Run 0 is the oracle; every other
configuration must reproduce it.

Comparison semantics follow the operators' contracts, exactly as the
reference's sweeps do:
* window tails run in DETERMINISTIC mode with host stages only (CB window
  CONTENTS are arrival-order-sensitive; multi-replica upstreams in DEFAULT
  mode legally reorder — the reference's ordered-mode tests exist for the
  same reason), compared exactly;
* ReduceTPU tails compare TOTALS only (a per-batch reduce emits one record
  per distinct key per batch, so the record COUNT legally varies with
  batching, while sum-combined totals are invariant);
* time-based FfatWindowsTPU tails run in DEFAULT mode with the full stage
  pool and compare (count, total) EXACTLY — TB window assignment is
  order-insensitive, so the min-folded watermark machinery must absorb any
  legal cross-replica reordering without a single late drop;
* plain tails compare (count, total) exactly — tuple multisets are
  batching/parallelism invariant.

Integer payloads keep every aggregation exact, so equality is bitwise."""

import random
import threading

import pytest

import windflow_tpu as wf

N_KEYS = 4
LENGTH = 320


def stream(seed):
    rnd = random.Random(seed)
    return [{"key": rnd.randrange(N_KEYS), "value": rnd.randrange(1000),
             "ts": i * 1000} for i in range(LENGTH)]


HOST_STAGES = ["map", "flatmap", "filter"]
ALL_STAGES = HOST_STAGES + ["map_tpu", "filter_tpu"]


def _mk_stage(kind, rnd):
    par = rnd.randint(1, 3)
    obs = rnd.randint(1, 32)
    if kind == "map":
        return (wf.Map_Builder(lambda t: {**t, "value": t["value"] + 7})
                .withParallelism(par).withOutputBatchSize(obs).build())
    if kind == "flatmap":
        def fm(t, shipper):
            shipper.push(dict(t))
            if t["value"] % 3 == 0:
                shipper.push({**t, "value": 1})
        return (wf.FlatMap_Builder(fm)
                .withParallelism(par).withOutputBatchSize(obs).build())
    if kind == "filter":
        return (wf.Filter_Builder(lambda t: t["value"] % 5 != 0)
                .withParallelism(par).withOutputBatchSize(obs).build())
    if kind == "map_tpu":
        return wf.MapTPU_Builder(
            lambda t: {**t, "value": t["value"] * 2}).build()
    return wf.FilterTPU_Builder(lambda t: (t["value"] & 3) != 3).build()


def _run_dag(seed, config_rnd):
    topo_rnd = random.Random(seed)           # fixed per seed: same topology
    n_stages = topo_rnd.randint(1, 3)
    # tb_window: time-based FfatWindowsTPU tail in DEFAULT mode — TB
    # assignment is order-insensitive, so even with multi-replica host
    # upstreams legally reordering tuples, the min-folded watermark must
    # keep results EXACT (the collector + staging-frontier machinery
    # under random topologies)
    tail = topo_rnd.choice(["none", "window", "reduce", "tb_window"])
    pool = HOST_STAGES if tail == "window" else ALL_STAGES
    kinds = [topo_rnd.choice(pool) for _ in range(n_stages)]
    do_split = topo_rnd.random() < 0.5
    do_merge = not do_split and topo_rnd.random() < 0.5
    mode = (wf.ExecutionMode.DETERMINISTIC if tail == "window"
            else wf.ExecutionMode.DEFAULT)

    accs = {}
    acc_lock = threading.Lock()   # sink replicas may run on pool threads

    def mk_sink(name):
        accs[name] = [0, 0]

        def s(r, ctx=None):
            if r is None:
                return
            v = r.value if hasattr(r, "value") else r["value"]
            with acc_lock:
                accs[name][0] += 1
                accs[name][1] += int(v)
        return wf.Sink_Builder(s).withParallelism(
            config_rnd.randint(1, 2)).build()

    # the host worker pool is a CONFIG dimension: pooled drains must
    # reproduce run 0's results bit-for-bit across every topology — and
    # so is whole-chain fusion (windflow_tpu/fusion): fused and unfused
    # sweeps of the same topology must be record-for-record identical —
    # and so is key compaction (windflow_tpu/parallel/compaction.py):
    # compacted and legacy paths of the same keyed consumers must be too
    # — and so are the Pallas kernels (windflow_tpu/kernels): the
    # kernel-backed and lax builds of the same programs must be too —
    # and so is the megastep executor (windflow_tpu/megastep): forcing
    # K>1 over these host-fed record edges exercises the K-granular
    # source pacing, the WF608 preflight walk on every fuzzed topology,
    # and the downgrade paths' K=1-verbatim contract (the fold itself
    # rides packed columnar edges — tests/test_megastep.py)
    cfg = wf.Config(host_worker_threads=config_rnd.choice([0, 0, 2, 4]),
                    whole_chain_fusion=config_rnd.choice([True, True,
                                                          False]),
                    key_compaction=config_rnd.choice([True, True,
                                                      False]),
                    pallas_kernels=config_rnd.choice(["auto", "auto",
                                                      "0"]),
                    megastep_sweeps=config_rnd.choice(["auto", "auto",
                                                       4]))
    g = wf.PipeGraph("fuzz", mode, wf.TimePolicy.EVENT, config=cfg)
    src_batch = config_rnd.randint(1, 64)
    mp = g.add_source(
        wf.Source_Builder(lambda: iter(stream(seed)))
        .withTimestampExtractor(lambda t: t["ts"])
        .withOutputBatchSize(src_batch).build())
    if do_merge:
        # a tb_window tail compiles for ONE batch capacity; all-TPU stage
        # chains preserve each source's capacity, so merged sources must
        # agree (the graph build enforces this with a clear error)
        b2 = (src_batch if tail == "tb_window"
              else config_rnd.randint(1, 64))
        mp2 = g.add_source(
            wf.Source_Builder(lambda: iter(stream(seed + 1)))
            .withTimestampExtractor(lambda t: t["ts"])
            .withOutputBatchSize(b2).build())
        mp = mp.merge(mp2)

    for kind in kinds:
        mp.add(_mk_stage(kind, config_rnd))

    def add_tail(pipe, name):
        if tail == "window":
            pipe.add(wf.Keyed_Windows_Builder(
                lambda items: sum(t["value"] for t in items))
                .withCBWindows(8, 4).withKeyBy(lambda t: t["key"])
                .withParallelism(config_rnd.randint(1, 3)).build())
        elif tail == "reduce":
            pipe.add(wf.ReduceTPU_Builder(
                lambda a, b: {"key": a["key"],
                              "value": a["value"] + b["value"],
                              "ts": b["ts"]})
                .withKeyBy(lambda t: t["key"]).build())
        elif tail == "tb_window":
            pipe.add(wf.Ffat_WindowsTPU_Builder(
                lambda t: t["value"], lambda a, b: a + b)
                .withTBWindows(16_000, 8_000)
                .withKeyBy(lambda t: t["key"])
                .withMaxKeys(N_KEYS).build())
        pipe.add_sink(mk_sink(name))

    if do_split:
        mp.split(lambda t: t["key"] % 2, 2)
        add_tail(mp.select(0), "b0")
        add_tail(mp.select(1), "b1")
    else:
        add_tail(mp, "b0")
    g.run()
    if tail == "reduce":   # per-batch partials: count legally varies
        return {k: v[1] for k, v in accs.items()}
    return {k: tuple(v) for k, v in accs.items()}


# seeds 2009/2011/2018/2031 are ordering-tie regressions: DETERMINISTIC
# window tails fed by multi-replica flatmap stages duplicate timestamps,
# and before origin-id tie-breaking (HostBatch.ids) the tuples' window
# assignment depended on which replica relayed them — equal counts,
# different totals across configurations
# the heaviest generic seeds (~6-16s each) ride the nightly run; the
# ordering-regression seeds and the remaining generic seeds keep the
# tier-1 fuzz coverage (404/707/1212 joined the nightly tier in the
# wfverify round's headroom pass, 505 in the calibration round's — the
# gate had drifted back toward the 870s budget)
@pytest.mark.parametrize("seed", [
    101, pytest.param(202, marks=pytest.mark.slow), 303,
    pytest.param(404, marks=pytest.mark.slow),
    pytest.param(505, marks=pytest.mark.slow), 606,
    pytest.param(707, marks=pytest.mark.slow),
    pytest.param(808, marks=pytest.mark.slow),
    pytest.param(909, marks=pytest.mark.slow),
    pytest.param(1212, marks=pytest.mark.slow),
    2009, 2011, 2018, 2031])
def test_dag_fuzz(seed):
    oracle = _run_dag(seed, random.Random(seed * 13 + 1))
    for run in range(2, 4):
        got = _run_dag(seed, random.Random(seed * 13 + run))
        assert got == oracle, (seed, run, got, oracle)

