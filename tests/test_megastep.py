"""Megastep executor suite (windflow_tpu/megastep.py, docs/PERF.md
round 15): fold K consecutive batch sweeps into ONE compiled scan
program on eligible staged edges.

The contracts pinned here:

- **Record-for-record A/B**: K=1 (the kill switch, per-batch cadence
  verbatim) vs K=4/K=8 produce identical sunk records across every
  foldable operator family — CB/TB FFAT windows, sorted and
  declared-dense reduces, dense-keys stateful map — wire compression
  on or off.
- **Dispatch pin**: one megastep = ONE ``megastep.<op>`` program
  dispatch in the jit registry serving K logical batches; the sweep
  ledger's per-hop ``dispatches_per_batch`` drops below 1 honestly.
- **Trace-lane / latency honesty**: flight-recorder spans and the
  end-to-end latency histogram are stamped PER LOGICAL BATCH at the
  megastep drain, never once per megastep.
- **Durability**: epochs round up to a multiple of K
  (``round_epoch_to_megastep``), land only between megasteps, and the
  chaos kill→restore→diff cell stays exactly-once under K=4.
- **WF608 preflight**: a forced ``WF_TPU_MEGASTEP=K`` graph whose edge
  cannot fold names the downgrade (the WF606/WF607 contract applied to
  the megastep plane); auto stays silent.
"""

import dataclasses
import json
import os
import warnings

import numpy as np
import pytest

import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.durability import chaos
from windflow_tpu.megastep import (MegastepPlane, resolve_megastep,
                                   round_epoch_to_megastep)
from windflow_tpu.monitoring.jit_registry import default_registry

FAMILIES = ("window_cb", "window_tb", "reduce_sorted", "reduce_dense",
            "stateful")

N = 4096
CAP = 256
KEYS = 8


# ---------------------------------------------------------------------------
# harness: a frames source (packed columnar staging — the eligible edge
# shape) feeding one foldable tail per family
# ---------------------------------------------------------------------------

def _frames_blob(n, nkeys=KEYS, seed=7):
    rng = np.random.default_rng(seed)
    rec = np.zeros(n, dtype=[("k", "<i8"), ("ts", "<i8"), ("v", "<f8")])
    rec["k"] = rng.integers(0, nkeys, n)
    rec["ts"] = np.arange(n, dtype=np.int64) * 500
    rec["v"] = rng.random(n)
    return rec.tobytes()


def _source(n=N, cap=CAP):
    blob = _frames_blob(n)
    step = cap * 24

    def chunks():
        for i in range(0, len(blob), step):
            yield blob[i:i + step]

    from windflow_tpu.io.frames import FrameSource
    return FrameSource(chunks, nv=1, fields=["v"], output_batch_size=cap)


def _tail(family):
    if family == "window_cb":
        return (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                           lambda a, b: a + b)
                .withCBWindows(64, 32).withKeyBy(lambda t: t["key"])
                .withMaxKeys(KEYS).withName("w").build())
    if family == "window_tb":
        return (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                           lambda a, b: a + b)
                .withTBWindows(16_000, 4_000)
                .withKeyBy(lambda t: t["key"]).withMaxKeys(KEYS)
                .withLateness(8_000).withName("w").build())
    if family == "reduce_sorted":
        return (wf.ReduceTPU_Builder(
                    lambda a, b: {"key": a["key"], "v": a["v"] + b["v"]})
                .withKeyBy(lambda t: t["key"]).withName("w").build())
    if family == "reduce_dense":
        return (wf.ReduceTPU_Builder(lambda a, b: a)
                .withKeyBy(lambda t: t["key"]).withMaxKeys(KEYS)
                .withSumCombiner().withName("w").build())
    if family == "stateful":
        def f(rec, st):
            st = {"acc": st["acc"] + rec["v"]}
            return {"key": rec["key"], "v": st["acc"]}, st
        return (wf.MapTPU_Builder(f)
                .withKeyBy(lambda t: t["key"])
                .withInitialState({"acc": jnp.float32(0)})
                .withNumKeySlots(KEYS).withDenseKeys()
                .withName("w").build())
    raise ValueError(family)


def _run(family, k, n=N, cap=CAP, **cfg_kw):
    """One graph run at megastep_sweeps=k; returns (sunk records,
    Megastep stats section, completed graph)."""
    fired = []
    # dense kinds under default key_compaction attach a host-admission
    # compactor — a DIFFERENT (deliberate, WF608-named) downgrade; off
    # here so the suite exercises the fold itself
    cfg_kw.setdefault("key_compaction", False)
    cfg = dataclasses.replace(wf.default_config, megastep_sweeps=k,
                              **cfg_kw)
    g = wf.PipeGraph(f"ms_{family}_{k}", config=cfg,
                     time_policy=wf.TimePolicy.EVENT)
    g.add_source(_source(n, cap)).add(_tail(family)).add_sink(
        wf.Sink_Builder(lambda r: fired.append(r)
                        if r is not None else None).build())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        g.run()
    return fired, g.stats()["Megastep"], g


def _norm(rs):
    out = []
    for r in rs:
        out.append(tuple(sorted(
            (k, round(float(v), 4) if isinstance(v, (float, np.floating))
             else (int(v) if isinstance(v, (int, np.integer)) else v))
            for k, v in r.items())))
    return out


# ---------------------------------------------------------------------------
# record-for-record A/B: K=1 vs K=4 / K=8
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("family", FAMILIES)
def test_ab_record_identical_k4(family):
    base, ms1, _ = _run(family, 1)
    fold, ms4, _ = _run(family, 4)
    assert _norm(base) == _norm(fold), family
    assert base, "empty output proves nothing"
    # K=1 is the kill switch: no plane, no edges
    assert ms1["k"] == 1 and ms1["edges"] == []
    e = ms4["edges"][0]
    assert e["k"] == 4 and e["megasteps"] > 0
    # every logical batch is accounted: folded + warm-up + fallback
    assert e["batches"] == e["megasteps"] * 4
    assert e["batches"] + e["warmup_batches"] + e["fallback_batches"] \
        == N // CAP


def test_ab_record_identical_k8_window():
    base, _, _ = _run("window_cb", 1, n=8192)
    fold, ms8, _ = _run("window_cb", 8, n=8192)
    assert _norm(base) == _norm(fold)
    e = ms8["edges"][0]
    assert e["k"] == 8 and e["megasteps"] > 0


def test_ab_record_identical_wire_on():
    """Wire compression composes: the scan body inlines the same wire
    decode the per-batch unpack runs."""
    base, _, _ = _run("window_cb", 1, wire_compression=True)
    fold, ms, _ = _run("window_cb", 4, wire_compression=True)
    assert _norm(base) == _norm(fold)
    assert ms["edges"][0]["megasteps"] > 0


def test_auto_resolves_per_backend():
    """'auto' keeps per-batch cadence on CPU (the dispatch fold pays off
    only when host pacing, not compute, bounds the edge) and a forced
    integer wins everywhere."""
    cfg = dataclasses.replace(wf.default_config, megastep_sweeps="auto")
    import jax
    expect = 1 if jax.default_backend() == "cpu" else 8
    assert resolve_megastep(cfg) == expect
    cfg = dataclasses.replace(wf.default_config, megastep_sweeps=4)
    assert resolve_megastep(cfg) == 4
    cfg = dataclasses.replace(wf.default_config, megastep_sweeps="1")
    assert resolve_megastep(cfg) == 1


# ---------------------------------------------------------------------------
# dispatch accounting: 1 program per K sweeps (jit registry + ledger)
# ---------------------------------------------------------------------------

def test_dispatch_pinned_one_program_per_megastep():
    before = dict(default_registry().dispatch_counts())
    _, ms, g = _run("window_cb", 4)
    after = default_registry().dispatch_counts()
    mega = {n: after[n] - before.get(n, 0)
            for n in after if n.startswith("megastep.")}
    e = ms["edges"][0]
    assert e["megasteps"] >= 2
    # the pin: exactly ONE megastep program dispatch per K-sweep group —
    # a fold that grew extra dispatches would show here
    assert sum(mega.values()) == e["megasteps"], mega
    # ...and the ledger divides it honestly: the tail hop served K
    # batches per dispatch, so dispatches/batch drops below 1
    hop = g.stats()["Sweep"]["per_hop"]["w"]
    assert hop["batches"] >= N // CAP    # + the FFAT EOS flush launch
    assert hop["dispatches"] < hop["batches"]
    assert hop["dispatches_per_batch"] < 1.0
    json.dumps(ms)      # ships in every stats payload


def test_k1_registers_no_megastep_programs():
    before = dict(default_registry().dispatch_counts())
    _, ms, _ = _run("reduce_dense", 1)
    after = default_registry().dispatch_counts()
    grew = [n for n in after if n.startswith("megastep.")
            and after[n] > before.get(n, 0)]
    assert grew == []
    assert ms["edges"] == []


# ---------------------------------------------------------------------------
# trace-lane / latency honesty at K granularity (flight recorder + p99)
# ---------------------------------------------------------------------------

def test_per_batch_spans_and_e2e_p99_under_k8():
    """A megastep serves K logical batches; the flight recorder and the
    end-to-end latency histogram must say K, not 1 — one span chain and
    one e2e sample PER BATCH, stamped at the drain."""
    n = 8192
    _, ms, g = _run("window_cb", 8, n=n, flight_recorder=True,
                    trace_sample_every=1)
    e = ms["edges"][0]
    assert e["megasteps"] >= 2
    ev = g._recorder.events()
    dispatched = [x for x in ev if x["stage"] == "dispatched"]
    sunk = [x for x in ev if x["stage"] == "sunk"]
    # per-batch honesty: a lazy implementation stamping once per
    # megastep would record ~megasteps spans, not ~batches
    assert len(dispatched) >= e["batches"]
    assert len(sunk) >= e["batches"]
    lat = g.stats()["Latency"]["end_to_end_usec"]
    assert lat["count"] >= e["batches"]
    assert lat["count"] > e["megasteps"]
    assert 0 < lat["p50"] <= lat["p99"]


# ---------------------------------------------------------------------------
# durability: epochs on megastep boundaries + chaos kill/restore A/B
# ---------------------------------------------------------------------------

def test_round_epoch_to_megastep_unit():
    """The configured cadence reads as LOGICAL sweeps and converts to
    driver sweeps (one driver sweep = K logical sweeps when folded):
    ceil(eps/K), so every epoch covers the same stream extent it
    covered per-batch."""
    plane = MegastepPlane(4)
    plane.edges.append(object())    # active needs >=1 edge
    cfg = dataclasses.replace(wf.default_config,
                              durability_epoch_sweeps=3)
    assert round_epoch_to_megastep(cfg, plane) == 1   # 3 -> 1 megastep
    assert cfg.durability_epoch_sweeps == 1
    cfg.durability_epoch_sweeps = 8
    assert round_epoch_to_megastep(cfg, plane) == 2   # 8 -> 2 megasteps
    cfg.durability_epoch_sweeps = 1
    assert round_epoch_to_megastep(cfg, plane) is None   # stable point
    assert cfg.durability_epoch_sweeps == 1
    inactive = MegastepPlane(1)
    cfg.durability_epoch_sweeps = 3
    assert round_epoch_to_megastep(cfg, inactive) is None
    assert cfg.durability_epoch_sweeps == 3


def _force_default(monkeypatch, **kw):
    """The chaos cell factories build from wf.default_config; pin the
    megastep knobs there for the cell's lifetime."""
    for k, v in kw.items():
        monkeypatch.setattr(wf.default_config, k, v)


def test_chaos_kill_restore_megastep_epochs(tmp_path, monkeypatch):
    """The exactly-once cell under K=4: the Kafka-fed CB-window family
    folds (wire on makes its record path a packed staged edge), its
    epoch cadence rounds 3->4 so every checkpoint quiesce lands between
    megasteps, a mid-epoch kill + restore replays — and the sunk output
    diffs record-for-record empty against the uninterrupted run."""
    _force_default(monkeypatch, megastep_sweeps=4, wire_compression=True)
    base = chaos.make_cell("window_cb", str(tmp_path / "ck_a"), n=N)
    chal = chaos.make_cell("window_cb", str(tmp_path / "ck_b"), n=N)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        gb = chaos.run_baseline(base["factory"])
        # the baseline actually folded, and the cell's epoch_sweeps=3
        # converted to whole megasteps (ceil(3/4) = 1 driver sweep)
        ms = gb.stats()["Megastep"]
        assert ms["k"] == 4 and ms["edges"][0]["megasteps"] > 0
        assert gb.config.durability_epoch_sweeps == 1
        # driver sweeps are K-granular, so the kill count is too
        gc = chaos.run_killed_and_restored(
            chal["factory"], chaos.KillSpec("mid_epoch", after=2))
    diff = chaos.diff_records(base["read"](), chal["read"]())
    assert diff is None, diff
    assert gc.stats()["Durability"]["restored_epoch"] is not None


def test_epoch_cadence_keeps_logical_sweep_meaning(tmp_path):
    """durability_epoch_sweeps reads as LOGICAL batch sweeps under a
    folded edge (round_epoch_to_megastep converts to driver sweeps):
    the K=4 run of the same stream commits at least as many epochs as
    K=1, never K x fewer."""
    def committed(k):
        fired = []
        cfg = dataclasses.replace(
            wf.default_config, megastep_sweeps=k, key_compaction=False,
            durability=str(tmp_path / f"ck_{k}"),
            durability_epoch_sweeps=4,
            punctuation_interval_usec=10 ** 12)
        g = wf.PipeGraph(f"ms_epoch_{k}", config=cfg,
                         time_policy=wf.TimePolicy.EVENT)
        g.add_source(_source()).add(_tail("window_cb")).add_sink(
            wf.Sink_Builder(lambda r: fired.append(r)).build())
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            g.run()
        return g.stats()["Durability"]["epochs_committed"]

    c1, c4 = committed(1), committed(4)
    assert c1 > 0
    # the conversion guard: without ceil(eps/K) the folded run would
    # cover ~K x more stream per epoch and commit ~c1/K epochs
    assert c4 >= c1


# ---------------------------------------------------------------------------
# WF608: forced K>1 downgrades are NAMED at preflight, auto is silent
# ---------------------------------------------------------------------------

def _cfgk(k, **kw):
    c = dataclasses.replace(wf.default_config, megastep_sweeps=k)
    for a, v in kw.items():
        setattr(c, a, v)
    return c


def _spec_source():
    return (wf.Source_Builder(lambda: iter(()))
            .withOutputBatchSize(256)
            .withRecordSpec({"key": np.int32(0),
                             "v": np.float32(0)}).build())


def _win():
    return (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                       lambda a, b: a + b)
            .withCBWindows(64, 32).withKeyBy(lambda t: t["key"])
            .withMaxKeys(8).build())


def _host_reduce():
    return (wf.Reduce_Builder(
        lambda item, st: st.__setitem__("n", st.get("n", 0) + 1), dict)
        .withKeyBy(lambda t: t["key"]).build())


def _wf608(g):
    return [d for d in g.check() if d.code == "WF608"]


def test_wf608_eligible_forced_is_clean():
    g = wf.PipeGraph("ok", config=_cfgk(8))
    g.add_source(_spec_source()).add(_win()).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    assert _wf608(g) == []


def test_wf608_host_operator_tail():
    g = wf.PipeGraph("host", config=_cfgk(8))
    g.add_source(_spec_source()).add(_host_reduce()).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    ds = _wf608(g)
    assert len(ds) == 1 and ds[0].severity == "warning"
    assert "host operator" in ds[0].message
    assert ds[0].hint       # documents the correctness-neutral downgrade


def test_wf608_specless_source():
    g = wf.PipeGraph("specless", config=_cfgk(8))
    g.add_source(wf.Source_Builder(lambda: iter(()))
                 .withOutputBatchSize(256).build()) \
        .add(_win()).add_sink(wf.Sink_Builder(lambda r: None).build())
    ds = _wf608(g)
    assert len(ds) == 1 and "spec" in ds[0].message


def test_wf608_compacted_key_space_and_the_fix():
    def graph(**cfg_kw):
        g = wf.PipeGraph("compacted", config=_cfgk(8, **cfg_kw))
        g.add_source(_spec_source()).add(
            wf.ReduceTPU_Builder(lambda a, b: a)
            .withKeyBy(lambda t: t["key"]).withMaxKeys(8)
            .withSumCombiner().build()).add_sink(
            wf.Sink_Builder(lambda r: None).build())
        return g

    ds = _wf608(graph(key_compaction=True))
    assert len(ds) == 1 and "compacted key space" in ds[0].message
    # the hint's own advice clears the warning
    assert _wf608(graph(key_compaction=False)) == []


def test_wf608_auto_is_silent():
    g = wf.PipeGraph("auto", config=_cfgk("auto"))
    g.add_source(_spec_source()).add(_host_reduce()).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    assert _wf608(g) == []


def test_wf608_fused_stateless_prelude_is_clean():
    """Stateless map/filter between source and window fuse into the
    tail segment — the effective tail still folds, no warning."""
    g = wf.PipeGraph("fused", config=_cfgk(8))
    p = g.add_source(_spec_source())
    p.add(wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "v": t["v"] * 2}).build())
    p.chain(wf.FilterTPU_Builder(lambda t: (t["key"] & 1) == 0).build())
    p.add(_win()).add_sink(wf.Sink_Builder(lambda r: None).build())
    assert _wf608(g) == []
