"""Merge/split topology tests in the reference's metamorphic-oracle style
(``/root/reference/tests/split_tests/``, ``tests/merge_tests/``, and the
``*_gpu`` variants): randomized parallelism/batch sweeps over DAGs with
splits and merges must reproduce run 0's sink accumulations exactly; TPU
variants mix device operators into the same topologies."""

import random

import pytest

import windflow_tpu as wf


def stream(n_keys, length):
    return [{"key": i % n_keys, "value": i} for i in range(length)]


class Acc:
    def __init__(self):
        self.total = 0
        self.count = 0

    def __call__(self, item, ctx=None):
        if item is not None:
            self.total += int(item["value"])
            self.count += 1

    @property
    def pair(self):
        return (self.total, self.count)


def run_split(mode, length, n_keys, par, batch):
    """Source → Map → split(2): even keys → Filter → Sink0,
    odd keys → Map(+100) → Sink1 (reference split_tests DAG shape)."""
    a0, a1 = Acc(), Acc()
    src = (wf.Source_Builder(lambda: iter(stream(n_keys, length)))
           .withOutputBatchSize(batch).build())
    pre = (wf.Map_Builder(lambda t: dict(t))
           .withParallelism(par[0]).withOutputBatchSize(batch).build())
    g = wf.PipeGraph("split", mode)
    mp = g.add_source(src).add(pre)
    mp.split(lambda t: t["key"] % 2, 2)
    (mp.select(0)
       .add(wf.Filter_Builder(lambda t: t["value"] % 3 == 0)
            .withParallelism(par[1]).withOutputBatchSize(batch).build())
       .add_sink(wf.Sink_Builder(a0).withParallelism(par[2]).build()))
    (mp.select(1)
       .add(wf.Map_Builder(lambda t: {"key": t["key"],
                                      "value": t["value"] + 100})
            .withParallelism(par[3]).withOutputBatchSize(batch).build())
       .add_sink(wf.Sink_Builder(a1).withParallelism(par[4]).build()))
    g.run()
    return a0.pair, a1.pair


@pytest.mark.parametrize("mode", [wf.ExecutionMode.DEFAULT,
                                  wf.ExecutionMode.DETERMINISTIC])
def test_split_metamorphic(mode):
    rnd = random.Random(11)
    length, n_keys = 900, 6
    reference = None
    for run in range(5):
        par = [rnd.randint(1, 4) for _ in range(5)]
        batch = rnd.randint(1, 9)
        got = run_split(mode, length, n_keys, par, batch)
        if reference is None:
            reference = got
        else:
            assert got == reference, f"run {run} diverged par={par}"
    # oracle: branch totals computed in plain python
    ev = [t for t in stream(n_keys, length) if t["key"] % 2 == 0]
    od = [t for t in stream(n_keys, length) if t["key"] % 2 == 1]
    exp0 = sum(t["value"] for t in ev if t["value"] % 3 == 0)
    exp1 = sum(t["value"] + 100 for t in od)
    assert reference[0][0] == exp0
    assert reference[1][0] == exp1


def test_split_multicast():
    """A split function returning an iterable multicasts the tuple to several
    branches (reference splitting signatures, splitting_emitter.hpp:54-62)."""
    length = 300
    a0, a1 = Acc(), Acc()
    src = (wf.Source_Builder(lambda: iter(stream(3, length)))
           .withOutputBatchSize(5).build())
    pre = wf.Map_Builder(lambda t: dict(t)).withOutputBatchSize(5).build()
    g = wf.PipeGraph("split_mc", wf.ExecutionMode.DEFAULT)
    mp = g.add_source(src).add(pre)
    mp.split(lambda t: (0, 1) if t["key"] == 0 else (t["key"] % 2,), 2)
    mp.select(0).add_sink(wf.Sink_Builder(a0).build())
    mp.select(1).add_sink(wf.Sink_Builder(a1).build())
    g.run()
    exp0 = sum(t["value"] for t in stream(3, length) if t["key"] in (0, 2))
    exp1 = sum(t["value"] for t in stream(3, length) if t["key"] in (0, 1))
    assert a0.total == exp0
    assert a1.total == exp1


def run_merge(mode, length, par, batch):
    """Two sources → (Map, Filter) → merge → Map → Sink (reference
    merge_tests shape: DAG fan-in via PipeGraph LCA)."""
    acc = Acc()
    g = wf.PipeGraph("merge", mode)
    s1 = (wf.Source_Builder(lambda: iter(stream(4, length)))
          .withOutputBatchSize(batch).build())
    s2 = (wf.Source_Builder(
            lambda: iter([{"key": 9, "value": 1000 + i}
                          for i in range(length // 2)]))
          .withOutputBatchSize(batch).build())
    p1 = g.add_source(s1).add(
        wf.Map_Builder(lambda t: {"key": t["key"], "value": t["value"] * 2})
        .withParallelism(par[0]).withOutputBatchSize(batch).build())
    p2 = g.add_source(s2).add(
        wf.Filter_Builder(lambda t: t["value"] % 2 == 0)
        .withParallelism(par[1]).withOutputBatchSize(batch).build())
    merged = p1.merge(p2)
    merged.add(
        wf.Map_Builder(lambda t: {"key": t["key"], "value": t["value"] + 1})
        .withParallelism(par[2]).withOutputBatchSize(batch).build())
    merged.add_sink(wf.Sink_Builder(acc).withParallelism(par[3]).build())
    g.run()
    return acc.pair


@pytest.mark.parametrize("mode", [wf.ExecutionMode.DEFAULT,
                                  wf.ExecutionMode.DETERMINISTIC])
def test_merge_metamorphic(mode):
    rnd = random.Random(5)
    length = 700
    reference = None
    for run in range(5):
        par = [rnd.randint(1, 4) for _ in range(4)]
        batch = rnd.randint(1, 8)
        got = run_merge(mode, length, par, batch)
        if reference is None:
            reference = got
        else:
            assert got == reference, f"run {run} diverged par={par}"
    exp = sum(2 * t["value"] + 1 for t in stream(4, length))
    exp += sum(v + 1 for v in range(1000, 1000 + length // 2) if v % 2 == 0)
    assert reference[0] == exp


def test_split_with_tpu_branch():
    """Split where one branch runs on TPU (reference split_tests_gpu): host
    branch and device branch must both see exactly their tuples."""
    length = 400
    a0, a1 = Acc(), Acc()
    src = (wf.Source_Builder(lambda: iter(stream(4, length)))
           .withOutputBatchSize(16).build())
    pre = wf.Map_Builder(lambda t: dict(t)).withOutputBatchSize(16).build()
    g = wf.PipeGraph("split_tpu", wf.ExecutionMode.DEFAULT)
    mp = g.add_source(src).add(pre)
    mp.split(lambda t: 0 if t["key"] < 2 else 1, 2)
    (mp.select(0)
       .add(wf.MapTPU_Builder(
            lambda t: {"key": t["key"], "value": t["value"] * 3}).build())
       .add_sink(wf.Sink_Builder(a0).build()))
    (mp.select(1)
       .add(wf.Map_Builder(lambda t: {"key": t["key"],
                                      "value": t["value"] * 5})
            .withOutputBatchSize(8).build())
       .add_sink(wf.Sink_Builder(a1).build()))
    g.run()
    exp0 = sum(3 * t["value"] for t in stream(4, length) if t["key"] < 2)
    exp1 = sum(5 * t["value"] for t in stream(4, length) if t["key"] >= 2)
    assert a0.total == exp0
    assert a1.total == exp1


def test_merge_into_tpu_keyed_reduce():
    """Merged pipes feeding a keyed TPU reduce (reference merge_tests_gpu
    ``_kb_`` variants): per-key sums must match the host oracle."""
    length = 360
    sums = {}

    def sink_fn(t, ctx=None):
        if t is not None:
            sums[int(t["key"])] = sums.get(int(t["key"]), 0) + int(t["value"])

    g = wf.PipeGraph("merge_tpu", wf.ExecutionMode.DEFAULT)
    s1 = (wf.Source_Builder(lambda: iter(stream(4, length)))
          .withOutputBatchSize(16).build())
    s2 = (wf.Source_Builder(lambda: iter(stream(4, length)))
          .withOutputBatchSize(16).build())
    p1 = g.add_source(s1).add(
        wf.Map_Builder(lambda t: dict(t)).withOutputBatchSize(16).build())
    p2 = g.add_source(s2).add(
        wf.Map_Builder(lambda t: dict(t)).withOutputBatchSize(16).build())
    merged = p1.merge(p2)
    merged.add(
        wf.ReduceTPU_Builder(
            lambda a, b: {"key": a["key"], "value": a["value"] + b["value"]})
        .withKeyBy(lambda t: t["key"]).build())
    merged.add_sink(wf.Sink_Builder(sink_fn).build())
    g.run()
    exp = {}
    for t in stream(4, length) * 2:
        exp[t["key"]] = exp.get(t["key"], 0) + t["value"]
    assert sums == exp
