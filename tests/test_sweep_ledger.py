"""Sweep-ledger + fusion-advisor contracts (docs/OBSERVABILITY.md
"Sweep ledger & fusion advisor"): exact per-hop dispatch counts on a
known 3-op chain (and the chained pair's REAL single dispatch), per-hop
bytes matching an independent XLA cost measurement, a seeded
donation-miss caught, the advisor's golden plan on the bench graph
shape, the OpenMetrics/trace/postmortem surfaces, and the kill-switch
off-path budget."""

import dataclasses
import importlib.util
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import default_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_BATCHES = 8
CAP = 256


def _cfg(tmp_path=None, **kw):
    if tmp_path is not None:
        kw.setdefault("log_dir", str(tmp_path))
    # these tests pin the UNFUSED sweep: they assert the pre-fusion
    # per-hop dispatch/byte contracts (one jitted dispatch per operator
    # hop); the fused-sweep contracts live in tests/test_fusion.py
    kw.setdefault("whole_chain_fusion", False)
    return dataclasses.replace(default_config, **kw)


def _spec():
    return {"key": np.int32(0), "v": np.float32(0.0)}


def _source(n=N_BATCHES * CAP, cap=CAP):
    # typed values, so host staging infers exactly the declared
    # int32/float32 record spec (untyped Python ints stage as int64 and
    # the payload model would understate the real lanes)
    return (wf.Source_Builder(
        lambda: iter({"key": np.int32(i % 8), "v": np.float32(i)}
                     for i in range(n)))
        .withName("src").withOutputBatchSize(cap)
        .withRecordSpec(_spec()).build())


def _three_op_graph(cfg, chained=False):
    """src -> ma -> fb -> mc -> snk; with ``chained`` the (ma, fb) pair
    fuses into ONE XLA program via MultiPipe.chain."""
    ma = (wf.MapTPU_Builder(lambda t: {"key": t["key"], "v": t["v"] * 2.0})
          .withName("ma").build())
    fb = (wf.FilterTPU_Builder(lambda t: (t["key"] & 1) == 0)
          .withName("fb").build())
    mc = (wf.MapTPU_Builder(lambda t: {"key": t["key"], "v": t["v"] + 1.0})
          .withName("mc").build())
    snk = wf.Sink_Builder(lambda t, ctx=None: None).withName("snk").build()
    g = wf.PipeGraph("sweep_app", wf.ExecutionMode.DEFAULT, config=cfg)
    pipe = g.add_source(_source())
    pipe.add(ma)
    pipe.chain(fb) if chained else pipe.add(fb)
    pipe.add(mc).add_sink(snk)
    return g


@pytest.fixture(scope="module")
def run_graph(tmp_path_factory):
    """One shared 3-op run: the per-hop dispatch, donation, OpenMetrics
    and postmortem contracts all read the same ledger section."""
    g = _three_op_graph(_cfg(tmp_path_factory.mktemp("sweep")))
    g.run()
    return g


# ---------------------------------------------------------------------------
# dispatch accounting
# ---------------------------------------------------------------------------

def test_three_op_chain_exact_dispatches(run_graph):
    sweep = run_graph.stats()["Sweep"]
    assert sweep["enabled"] is True
    for name in ("ma", "fb", "mc"):
        hop = sweep["per_hop"][name]
        assert hop["batches"] == N_BATCHES
        assert hop["dispatches"] == N_BATCHES
        assert hop["dispatches_per_batch"] == 1.0
        assert hop["capacity"] == CAP
    # hop-boundary residency: ma/fb feed the next TPU hop on device
    # (fusion fuel); mc's output leaves for the host sink
    assert sweep["per_hop"]["ma"]["resident_output"] is True
    assert sweep["per_hop"]["fb"]["resident_output"] is True
    assert sweep["per_hop"]["mc"]["resident_output"] is False
    assert sweep["totals"]["dispatches_per_batch"] == 3.0
    # JSON-clean: the section ships in every NEW_REPORT payload
    json.dumps(sweep)


def test_chained_pair_shows_one_dispatch(tmp_path):
    """ops/chained.py fusion is visible in the ledger: the fused ma|fb
    hop pays ONE jitted dispatch per batch where the unchained pair
    (previous test) pays two."""
    g = _three_op_graph(_cfg(tmp_path), chained=True)
    g.run()
    sweep = g.stats()["Sweep"]
    assert "ma" not in sweep["per_hop"] and "fb" not in sweep["per_hop"]
    hop = sweep["per_hop"]["ma|fb"]
    assert hop["batches"] == N_BATCHES
    assert hop["dispatches"] == N_BATCHES
    assert hop["dispatches_per_batch"] == 1.0
    assert sweep["totals"]["dispatches_per_batch"] == 2.0


# ---------------------------------------------------------------------------
# byte attribution vs an independent XLA cost measurement
# ---------------------------------------------------------------------------

def test_per_hop_bytes_match_independent_cost(tmp_path, monkeypatch):
    """The map hop's attributed bytes/batch must match what XLA's
    compiled cost analysis reports for the IDENTICAL program measured
    outside the ledger, and the totals must sum the hops."""
    import jax
    import jax.numpy as jnp
    from windflow_tpu.monitoring import jit_registry

    monkeypatch.setattr(jit_registry, "COST_MODE", "compiled")
    fn = lambda t: {"key": t["key"], "v": t["v"] * 2.0}
    ma = wf.MapTPU_Builder(fn).withName("bytes_ma").build()
    snk = wf.Sink_Builder(lambda t, ctx=None: None).withName("snk").build()
    g = wf.PipeGraph("sweep_bytes", wf.ExecutionMode.DEFAULT,
                     config=_cfg(tmp_path))
    g.add_source(_source()).add(ma).add_sink(snk)
    g.run()
    sweep = g.stats()["Sweep"]
    hop = sweep["per_hop"]["bytes_ma"]
    assert hop["dispatches_per_batch"] == 1.0

    def step(payload, valid):
        return jax.vmap(fn)(payload)

    payload = {"key": jnp.zeros(CAP, jnp.int32),
               "v": jnp.zeros(CAP, jnp.float32)}
    valid = jnp.ones(CAP, bool)
    ca = jax.jit(step).lower(payload, valid).compile().cost_analysis()
    d = ca[0] if isinstance(ca, (list, tuple)) else ca
    measured = float(d["bytes accessed"])
    assert measured > 0
    assert abs(hop["bytes_per_batch"] - measured) / measured < 0.10, \
        (hop, measured)
    # the per-hop bytes sum to the totals the roofline decomposition
    # reads (bench.py roofline.per_hop / attributed_fraction)
    total = sum(h["bytes_per_tuple"] for h in sweep["per_hop"].values()
                if h.get("bytes_per_tuple") is not None)
    assert abs(sweep["totals"]["bytes_per_tuple"] - total) < 0.1
    # payload-vs-overhead split against the declared record spec:
    # int32 + float32 payload + ts/valid lanes = 17 B/tuple model
    assert hop["payload_bytes_per_tuple"] == 17
    assert hop["excess_vs_model"] == pytest.approx(
        hop["bytes_per_tuple"] / 17, abs=0.01)


@pytest.mark.slow
def test_window_hop_bytes_match_kernel_measurement(tmp_path, monkeypatch):
    """Acceptance-shaped: on a bench-shaped pipeline the WINDOW hop's
    per-batch attributed bytes land within 10% of the raw FFAT kernel
    step's measured bytes (the roofline.measured_bytes_per_step
    methodology of bench.py, same shape, measured independently)."""
    import math

    import jax
    import jax.numpy as jnp

    from windflow_tpu.monitoring import jit_registry
    from windflow_tpu.windows.ffat_kernels import (make_ffat_state,
                                                   make_ffat_step)

    monkeypatch.setattr(jit_registry, "COST_MODE", "compiled")
    K, WIN, SLIDE = 16, 64, 16
    lift = lambda t: t["v"]
    comb = lambda a, b: a + b
    key_fn = lambda t: t["key"]
    win = (wf.Ffat_WindowsTPU_Builder(lift, comb)
           .withCBWindows(WIN, SLIDE).withKeyBy(key_fn)
           .withMaxKeys(K).withName("slow_win").build())
    snk = wf.Sink_Builder(lambda t, ctx=None: None).withName("snk").build()
    g = wf.PipeGraph("sweep_win", wf.ExecutionMode.DEFAULT,
                     config=_cfg(tmp_path))
    g.add_source(_source(n=32 * CAP)).add(win).add_sink(snk)
    g.run()
    hop = g.stats()["Sweep"]["per_hop"]["slow_win"]
    # 32 data batches; the EOS flush may add one synthetic batch
    assert hop["batches"] in (32, 33)

    Pn = math.gcd(WIN, SLIDE)
    step_fn = make_ffat_step(CAP, K, Pn, WIN // Pn, SLIDE // Pn,
                             lift, comb, key_fn)
    state = make_ffat_state(jnp.zeros((), jnp.float32), K, WIN // Pn)
    payload = {"key": jnp.zeros(CAP, jnp.int32),
               "v": jnp.zeros(CAP, jnp.float32)}
    ts = jnp.zeros(CAP, jnp.int64)
    valid = jnp.ones(CAP, bool)
    ca = (jax.jit(step_fn, donate_argnums=(0,))
          .lower(state, payload, ts, valid).compile().cost_analysis())
    d = ca[0] if isinstance(ca, (list, tuple)) else ca
    measured = float(d["bytes accessed"])
    assert measured > 0
    assert abs(hop["bytes_per_batch"] - measured) / measured < 0.10, \
        (hop, measured)
    # the steady-state number excludes the EOS flush entirely: exact
    # (same program, same cost table) — what bench.py's
    # roofline.attributed_fraction compares against the kernel step
    steady = hop["steady_bytes_per_tuple"] * CAP
    assert abs(steady - measured) / measured < 0.01, (steady, measured)


# ---------------------------------------------------------------------------
# donation misses
# ---------------------------------------------------------------------------

def test_seeded_donation_miss_caught(run_graph):
    """MapTPU's step returns same-shape/dtype buffers without donating
    its inputs: every batch pays a whole-buffer copy the ledger must
    flag as a donation miss."""
    sweep = run_graph.stats()["Sweep"]
    miss = sweep["per_hop"]["ma"]["donation_miss"]
    assert miss["candidate_leaves"] >= 1
    assert miss["bytes_per_batch"] > 0
    assert miss["donates_some_args"] is False
    assert sweep["totals"]["donation_miss_bytes_per_batch"] > 0


def test_ffat_state_donation_recorded(tmp_path):
    """The FFAT step donates its state (argnum 0): the registry's
    donation audit must record it, so the ledger never flags the state
    round-trip as a miss."""
    win = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                      lambda a, b: a + b)
           .withCBWindows(64, 16).withKeyBy(lambda t: t["key"])
           .withMaxKeys(16).withName("don_win").build())
    snk = wf.Sink_Builder(lambda t, ctx=None: None).withName("snk").build()
    g = wf.PipeGraph("sweep_don", wf.ExecutionMode.DEFAULT,
                     config=_cfg(tmp_path))
    g.add_source(_source()).add(win).add_sink(snk)
    g.run()
    from windflow_tpu.monitoring.jit_registry import default_registry
    entry = default_registry().snapshot()["don_win"]
    assert entry["donation"]["donated_argnums"] == [0]


# ---------------------------------------------------------------------------
# fusion advisor
# ---------------------------------------------------------------------------

def _bench_shape_graph():
    """The bench.py staged-e2e pipeline shape (map + chained filter ->
    keyed FFAT window -> sink) the advisor's golden plan targets."""
    src = (wf.Source_Builder(lambda: iter(()))
           .withOutputBatchSize(4096).withName("src")
           .withRecordSpec({"key": np.int32(0), "v0": np.float32(0.0)})
           .build())
    m = wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "v0": t["v0"] * 1.5 + 1.0}).build()
    f = wf.FilterTPU_Builder(lambda t: (t["key"] & 7) != 7).build()
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v0"],
                                    lambda a, b: a + b)
         .withCBWindows(1024, 128).withKeyBy(lambda t: t["key"])
         .withMaxKeys(256).build())
    snk = wf.Sink_Builder(lambda r: None).build()
    g = wf.PipeGraph("bench_shape")
    pipe = g.add_source(src)
    pipe.add(m)
    pipe.chain(f)
    pipe.add(w).add_sink(snk)
    return g


def test_advisor_golden_plan_on_bench_graph():
    """>= 1 ranked fusion candidate on the bench pipeline, with
    projected bytes- and dispatches-saved (the acceptance contract):
    the already-chained map|filter pair plus the window hop lower into
    one program under whole-chain fusion."""
    from windflow_tpu.analysis.fusion import plan
    p = plan(_bench_shape_graph())
    assert len(p["chains"]) >= 1
    top = p["chains"][0]
    assert top["ops"] == ["map_tpu|filter_tpu", "ffat_windows_tpu"]
    assert top["links"] == ["whole_chain"]
    assert top["provable_now"] is False
    assert top["dispatches_saved_per_batch"] >= 1
    assert top["projected_bytes_saved_per_batch"] > 0
    json.dumps(p)


def test_advisor_unchained_pair_is_provable_now(tmp_path):
    """A map->filter pair composed with add() (not chain()) is a fusion
    candidate TODAY: the advisor must rank it as provable via
    MultiPipe.chain, with measured dispatch counts when given a live
    sweep section."""
    from windflow_tpu.analysis.fusion import plan
    g = _three_op_graph(_cfg(tmp_path))
    g.run()
    p = plan(g, sweep=g.stats()["Sweep"])
    assert p["chains"], p
    top = p["chains"][0]
    assert top["ops"] == ["ma", "fb", "mc"]
    assert all(k == "chainable" for k in top["links"])
    assert top["provable_now"] is True
    assert top["basis"] == "measured"
    assert top["dispatches_per_batch_now"] == 3.0
    assert top["dispatches_saved_per_batch"] == 2.0
    assert top["projected_bytes_saved_per_batch"] > 0


@pytest.mark.slow
def test_advisor_cli_emits_ranked_json_plan(tmp_path):
    """tools/wf_advisor.py round trip: module factory -> ranked JSON
    plan on stdout, exit 0 when candidates exist."""
    app = tmp_path / "advisor_app.py"
    app.write_text(
        "import numpy as np\n"
        "import windflow_tpu as wf\n\n"
        "def make_graph():\n"
        "    src = (wf.Source_Builder(lambda: iter(()))\n"
        "           .withOutputBatchSize(512).withName('src')\n"
        "           .withRecordSpec({'key': np.int32(0),\n"
        "                            'v': np.float32(0.0)}).build())\n"
        "    a = wf.MapTPU_Builder(\n"
        "        lambda t: {'key': t['key'], 'v': t['v'] * 2.0}).build()\n"
        "    b = wf.FilterTPU_Builder(\n"
        "        lambda t: (t['key'] & 1) == 0).build()\n"
        "    snk = wf.Sink_Builder(lambda r: None).build()\n"
        "    g = wf.PipeGraph('cli_app')\n"
        "    g.add_source(src).add(a).add(b).add_sink(snk)\n"
        "    return g\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(tmp_path))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_advisor.py"),
         "advisor_app", "--json"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=180)
    assert r.returncode == 0, r.stderr
    p = json.loads(r.stdout)
    assert p["graph"] == "cli_app"
    assert p["chains"][0]["ops"] == ["map_tpu", "filter_tpu"]
    assert p["chains"][0]["provable_now"] is True


# ---------------------------------------------------------------------------
# surfaces: OpenMetrics, trace metadata, postmortem + wf_doctor
# ---------------------------------------------------------------------------

def test_openmetrics_sweep_families_render_and_parse(run_graph):
    from windflow_tpu.monitoring.openmetrics import (parse_exposition,
                                                     render_openmetrics)
    fams = parse_exposition(render_openmetrics(run_graph.stats()))
    disp = fams["wf_sweep_dispatches_per_batch"]["samples"]
    ops = {labels["operator"]: value for _, labels, value in disp}
    assert ops["ma"] == 1.0 and ops["fb"] == 1.0 and ops["mc"] == 1.0
    assert "wf_sweep_bytes_per_tuple" in fams
    miss = fams["wf_sweep_donation_miss_bytes_per_batch"]["samples"]
    assert any(v > 0 for _, _, v in miss)


def test_dump_trace_metadata_carries_sweep(run_graph, tmp_path):
    path = run_graph.dump_trace(str(tmp_path / "t_trace.json"))
    with open(path) as f:
        trace = json.load(f)
    sweep = trace["otherData"]["sweep"]
    assert sweep["enabled"] is True
    assert "ma" in sweep["per_hop"]


def _load_doctor():
    spec = importlib.util.spec_from_file_location(
        "wf_doctor", os.path.join(REPO, "tools", "wf_doctor.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_postmortem_sweep_section_roundtrips_wf_doctor(run_graph,
                                                       tmp_path):
    doctor = _load_doctor()
    d = run_graph.dump_postmortem(str(tmp_path / "bundle"),
                                  reason="sweep test")
    bundle = doctor.load_bundle(d)
    doctor.validate(bundle)
    assert bundle["sections"]["sweep.json"]["enabled"] is True
    diag = doctor.diagnose(bundle)
    assert diag["sweep_top_hop"]["op"] in ("ma", "fb", "mc")
    assert "ma" in diag["donation_misses"]
    text = doctor.render_text(diag)
    assert "hottest hop" in text and "donation miss" in text
    # a corrupted sweep section must fail --check, not render garbage
    sweep_path = os.path.join(d, "sweep.json")
    with open(sweep_path) as f:
        sweep = json.load(f)
    sweep["per_hop"]["ma"]["bytes_per_tuple"] = "lots"
    with open(sweep_path, "w") as f:
        json.dump(sweep, f)
    with pytest.raises(doctor.BundleError):
        doctor.validate(doctor.load_bundle(d))


# ---------------------------------------------------------------------------
# kill switch
# ---------------------------------------------------------------------------

def test_kill_switch_off_path_budget(tmp_path):
    g = _three_op_graph(_cfg(tmp_path, sweep_ledger=False))
    g.run()
    assert g._ledger is None
    assert g.stats()["Sweep"] == {"enabled": False}
    # off-path budget (mirrors test_health_disabled_off_path): the
    # disabled read site is ONE `is not None` check — micro-assert it
    # stays orders of magnitude under a real section build.  The
    # per-batch path carries no ledger hook at all either way (the
    # dispatch counter belongs to the compile watcher).
    t0 = time.perf_counter()
    for _ in range(10_000):
        g._sweep_section()
    per_call = (time.perf_counter() - t0) / 10_000
    assert per_call < 5e-6, \
        f"disabled sweep section costs {per_call * 1e6:.2f}us/call"
    from windflow_tpu.monitoring.openmetrics import render_openmetrics
    assert "wf_sweep_" not in render_openmetrics(g.stats())
