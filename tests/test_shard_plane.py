"""Shard-plane + reshard-advisor contracts (docs/OBSERVABILITY.md
"Shard plane & reshard advisor"): a seeded Zipf-skew keyby graph whose
hot key/shard the ledger provably names, the sketch-vs-exact accuracy
bound, in-program sketches on device-keyby and fused-chain edges with
ZERO extra dispatches, mesh per-key-shard attribution + the ICI model,
the OpenMetrics/trace/postmortem surfaces, the reshard plan contract,
and the kill-switch off-path budget."""

import dataclasses
import importlib.util
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import default_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_BATCHES = 16
CAP = 256
N = N_BATCHES * CAP
HOT_KEY = 7
PAR = 4


def _cfg(tmp_path=None, **kw):
    if tmp_path is not None:
        kw.setdefault("log_dir", str(tmp_path))
    return dataclasses.replace(default_config, **kw)


def _zipf_keys(n=N, n_keys=64, hot=HOT_KEY, share=0.4, seed=5):
    rng = np.random.default_rng(seed)
    ks = rng.integers(0, n_keys, n)
    ks[rng.random(n) < share] = hot
    return ks


ZIPF_KEYS = _zipf_keys()


def _records(ks=ZIPF_KEYS):
    return iter({"key": int(k), "v": float(i)} for i, k in enumerate(ks))


def _zipf_graph(cfg, name="zipf_app", par=PAR):
    """src -> keyed ReduceTPU at parallelism ``par`` -> sink: the keyed
    staging emitter partitions by splitmix64(key) % par, so the seeded
    hot key pins one shard."""
    src = (wf.Source_Builder(_records).withOutputBatchSize(CAP)
           .withName("src").build())
    red = (wf.ReduceTPU_Builder(
        lambda a, b: {"key": b["key"], "v": a["v"] + b["v"]})
        .withKeyBy(lambda t: t["key"]).withParallelism(par)
        .withName("red").build())
    snk = wf.Sink_Builder(lambda t, ctx=None: None).withName("snk").build()
    g = wf.PipeGraph(name, wf.ExecutionMode.DEFAULT, config=cfg)
    g.add_source(src).add(red).add_sink(snk)
    return g


@pytest.fixture(scope="module")
def zipf_run(tmp_path_factory):
    """One shared seeded-skew run: the attribution, accuracy, surface,
    and advisor contracts all read the same ledger section."""
    g = _zipf_graph(_cfg(tmp_path_factory.mktemp("shard")))
    g.run()
    return g, g.stats()["Shard"]


# ---------------------------------------------------------------------------
# seeded-skew attribution: the acceptance contract
# ---------------------------------------------------------------------------

def _expected_shard_counts(ks=ZIPF_KEYS, par=PAR):
    from windflow_tpu.parallel.emitters import splitmix64_int
    out = np.zeros(par, np.int64)
    for k in ks:
        out[splitmix64_int(int(k)) % par] += 1
    return out


def test_zipf_hot_shard_and_key_attributed(zipf_run):
    _, sec = zipf_run
    assert sec["enabled"] is True
    load = sec["per_op"]["red"]["load"]
    expected = _expected_shard_counts()
    # per-shard load is EXACT on the keyed staging edge (the counts are
    # the routing's own placement over the full key column)
    assert load["tuples"] == [int(c) for c in expected]
    assert load["total_tuples"] == N
    assert load["hot_shard"] == int(expected.argmax())
    assert load["imbalance_ratio"] == pytest.approx(
        expected.max() / expected.mean(), abs=1e-3)
    assert load["imbalance_ratio"] > 1.5      # the skew is visible
    # the injected hot key is ranked first and placed on its real shard
    top = load["hot_keys"][0]
    assert top["key"] == HOT_KEY
    assert top["shard"] == load["hot_shard"]
    assert load["hot_key_share"] == pytest.approx(0.4, abs=0.05)
    # graph totals point at the same operator
    assert sec["totals"]["max_imbalance_op"] == "red"
    assert sec["totals"]["hot_key_op"] == "red"
    json.dumps(sec)     # ships in every NEW_REPORT payload


#: absolute slack: expected CMS collision mass is ~total/width per row
SKETCH_SLACK = 4 * N / 2048


def test_sketch_estimate_within_accuracy_bound(zipf_run):
    """Count-min estimates never undercount, and with 64 distinct keys
    against a 4x2048 sketch the collision mass keeps the hot key's
    estimate within a few percent of the exact count."""
    _, sec = zipf_run
    load = sec["per_op"]["red"]["load"]
    assert load["basis"] == "cms"     # unbounded key space: sketched
    true_hot = int((ZIPF_KEYS == HOT_KEY).sum())
    est = load["hot_keys"][0]["est_tuples"]
    assert est >= true_hot
    assert est <= true_hot * 1.05 + SKETCH_SLACK


def test_per_replica_runtime_attribution(zipf_run):
    """The gauges that existed only per-operator are now per shard:
    each replica row carries its own queue/lag/dispatch/latency (and
    HBM bytes where the cost table attributed)."""
    g, sec = zipf_run
    entry = sec["per_op"]["red"]
    assert entry["parallelism"] == PAR and entry["keyed"] is True
    reps = entry["replicas"]
    assert [r["shard"] for r in reps] == list(range(PAR))
    # every shard processed its own partition: inputs track the load
    load = sec["per_op"]["red"]["load"]
    for r, expect in zip(reps, load["tuples"]):
        assert r["inputs"] == expect
        assert r["queue_depth"] == 0          # drained at EOS
        assert r["dispatches"] >= 1
    # non-keyed ops carry replica attribution too (no load table)
    assert "load" not in sec["per_op"]["snk"]
    assert len(sec["per_op"]["snk"]["replicas"]) == 1


# ---------------------------------------------------------------------------
# in-program sketches: zero extra dispatches
# ---------------------------------------------------------------------------

def _split_dispatches():
    from windflow_tpu.monitoring.jit_registry import default_registry
    e = default_registry().snapshot().get("emitter.device_keyby_split")
    return (e or {}).get("dispatches", 0)


def _dev_keyby_graph(cfg, name):
    import jax.numpy as jnp
    src = (wf.Source_Builder(_records).withOutputBatchSize(CAP)
           .withName("src").build())
    m = (wf.MapTPU_Builder(lambda t: {"key": t["key"], "v": t["v"] * 2.0})
         .withName("m").build())
    st = (wf.MapTPU_Builder(
        lambda t, s: ({"key": t["key"], "run": s + t["v"]}, s + t["v"]))
        .withInitialState(jnp.zeros((), jnp.float32))
        .withKeyBy(lambda t: t["key"]).withNumKeySlots(64).withDenseKeys()
        .withParallelism(2).withName("st").build())
    snk = wf.Sink_Builder(lambda t, ctx=None: None).withName("snk").build()
    g = wf.PipeGraph(name, wf.ExecutionMode.DEFAULT, config=cfg)
    g.add_source(src).add(m).add(st).add_sink(snk)
    return g


def test_device_keyby_in_program_sketch_zero_extra_dispatches(tmp_path):
    """The TPU->TPU keyby edge's sketch rides INSIDE the split program:
    the ledger-on run pays exactly as many split dispatches as the
    ledger-off run, and the merged sketch still names the hot key."""
    d0 = _split_dispatches()
    g_off = _dev_keyby_graph(_cfg(tmp_path, shard_ledger=False), "dk_off")
    g_off.run()
    off_disp = _split_dispatches() - d0
    assert off_disp == N_BATCHES
    d1 = _split_dispatches()
    g_on = _dev_keyby_graph(_cfg(tmp_path), "dk_on")
    g_on.run()
    on_disp = _split_dispatches() - d1
    assert on_disp == off_disp       # zero extra dispatches
    load = g_on.stats()["Shard"]["per_op"]["st"]["load"]
    assert load["total_tuples"] == N
    assert load["hot_keys"][0]["key"] == HOT_KEY
    # per-shard counts match the split program's own placement
    from windflow_tpu.parallel.emitters import splitmix64_int
    expected = np.zeros(2, np.int64)
    for k in ZIPF_KEYS:
        expected[splitmix64_int(int(k)) % 2] += 1
    assert load["tuples"] == [int(c) for c in expected]


def test_fused_chain_sketch_rides_the_chain_program(tmp_path):
    """A chained pair forwarding a downstream KEYBY consumer's keys
    extracts them in-program (PR 7); the sketch folds into that SAME
    program — dispatches per batch stay 1.0 and the hot key surfaces."""
    import jax.numpy as jnp
    cfg = _cfg(tmp_path, whole_chain_fusion=False)
    src = (wf.Source_Builder(_records).withOutputBatchSize(CAP)
           .withName("src").build())
    ma = (wf.MapTPU_Builder(lambda t: {"key": t["key"], "v": t["v"] * 2.0})
          .withName("ma").build())
    fb = (wf.FilterTPU_Builder(lambda t: t["v"] >= 0.0)
          .withName("fb").build())
    st = (wf.MapTPU_Builder(
        lambda t, s: ({"key": t["key"], "run": s + t["v"]}, s + t["v"]))
        .withInitialState(jnp.zeros((), jnp.float32))
        .withKeyBy(lambda t: t["key"]).withNumKeySlots(64).withDenseKeys()
        .withName("st").build())
    snk = wf.Sink_Builder(lambda t, ctx=None: None).withName("snk").build()
    g = wf.PipeGraph("fused_sketch", wf.ExecutionMode.DEFAULT, config=cfg)
    pipe = g.add_source(src)
    pipe.add(ma)
    pipe.chain(fb)
    pipe.add(st).add_sink(snk)
    g.run()
    sweep = g.stats()["Sweep"]
    assert sweep["per_hop"]["ma|fb"]["dispatches_per_batch"] == 1.0
    load = g.stats()["Shard"]["per_op"]["st"]["load"]
    assert load["total_tuples"] == N
    assert load["hot_keys"][0]["key"] == HOT_KEY


def test_chain_into_parallel_keyby_counts_once(tmp_path):
    """A chained pair feeding a keyed consumer at parallelism 2 routes
    through a DeviceKeyByEmitter whose split program sketches the
    stream; the chain program must NOT sketch it again (regression:
    total_tuples would read 2x)."""
    import jax.numpy as jnp
    cfg = _cfg(tmp_path, whole_chain_fusion=False)
    src = (wf.Source_Builder(_records).withOutputBatchSize(CAP)
           .withName("src").build())
    ma = (wf.MapTPU_Builder(lambda t: {"key": t["key"], "v": t["v"] * 2.0})
          .withName("ma").build())
    fb = (wf.FilterTPU_Builder(lambda t: t["v"] >= 0.0)
          .withName("fb").build())
    st = (wf.MapTPU_Builder(
        lambda t, s: ({"key": t["key"], "run": s + t["v"]}, s + t["v"]))
        .withInitialState(jnp.zeros((), jnp.float32))
        .withKeyBy(lambda t: t["key"]).withNumKeySlots(64).withDenseKeys()
        .withParallelism(2).withName("st").build())
    snk = wf.Sink_Builder(lambda t, ctx=None: None).withName("snk").build()
    g = wf.PipeGraph("chain_par_keyby", wf.ExecutionMode.DEFAULT,
                     config=cfg)
    pipe = g.add_source(src)
    pipe.add(ma)
    pipe.chain(fb)
    pipe.add(st).add_sink(snk)
    g.run()
    load = g.stats()["Shard"]["per_op"]["st"]["load"]
    assert load["total_tuples"] == N          # counted exactly once
    assert sum(load["tuples"]) == N
    assert load["hot_keys"][0]["key"] == HOT_KEY


# ---------------------------------------------------------------------------
# mesh: per-key-shard load + the ICI model
# ---------------------------------------------------------------------------

def _mesh_graph(n_keys=16, aligned=True):
    from windflow_tpu.parallel import mesh as M
    mesh = M.make_mesh(8, data=2)
    cfg = dataclasses.replace(default_config, mesh=mesh,
                              key_aligned_ingest=aligned)
    ks = _zipf_keys(n=8 * 128, n_keys=n_keys, hot=3, share=0.5)
    src = (wf.Source_Builder(lambda: iter(
        {"key": int(k), "v": float(i)} for i, k in enumerate(ks)))
        .withOutputBatchSize(128).build())
    win = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"], lambda a, b: a + b)
           .withCBWindows(8, 4).withKeyBy(lambda t: t["key"])
           .withMaxKeys(n_keys).withName("mwin").build())
    g = wf.PipeGraph("mesh_shard", wf.ExecutionMode.DEFAULT, config=cfg)
    g.add_source(src).add(win).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    return g, ks


def test_mesh_key_shard_attribution_and_ici_model():
    g, ks = _mesh_graph()
    g.run()
    entry = g.stats()["Shard"]["per_op"]["mwin"]
    load = entry["load"]
    # dense_range placement: chip i owns keys [i*K/kk, (i+1)*K/kk) —
    # per-key-shard load is EXACT (dense histogram over max_keys)
    assert load["placement"] == "dense_range"
    assert load["basis"] == "exact"
    hist = np.bincount(ks, minlength=16)
    expected = hist.reshape(4, 4).sum(axis=1)     # key axis = 4
    assert load["tuples"] == [int(c) for c in expected]
    assert load["hot_shard"] == 0                 # key 3 lives on shard 0
    assert load["hot_keys"][0]["key"] == 3
    assert load["hot_keys"][0]["shard"] == 0
    # ICI model: this host-fed window takes KEY-ALIGNED ingest by
    # default since the wire round — only the within-column data-axis
    # hop remains, and the model names it
    ici = entry["ici"]
    assert ici["collective"] == "all_gather(data|key-aligned)"
    assert ici["mesh"] == {"data": 2, "key": 4}
    assert ici["ici_bytes_per_tuple"] > 0
    assert g.stats()["Shard"]["totals"]["ici_bytes_per_tuple"] > 0
    # kill switch restores the data-sharded ingest + full all_gather,
    # with MORE modeled ICI bytes than the aligned path
    g2, _ = _mesh_graph(aligned=False)
    g2.run()
    ici2 = g2.stats()["Shard"]["per_op"]["mwin"]["ici"]
    assert ici2["collective"] == "all_gather(data)"
    assert ici2["ici_bytes_per_tuple"] > ici["ici_bytes_per_tuple"]


def test_mesh_arbitrary_keys_mod_placement():
    """A mesh keyed reduce WITHOUT withMaxKeys hash-shards lanes to
    their owner chip by uint32(key) % n — the sketch mirrors that
    placement (regression: the load table read all zeros)."""
    from windflow_tpu.parallel import mesh as M
    mesh = M.make_mesh(8, data=2)
    cfg = dataclasses.replace(default_config, mesh=mesh)
    ks = _zipf_keys(n=8 * 128, n_keys=1 << 20, hot=9, share=0.5, seed=3)
    src = (wf.Source_Builder(lambda: iter(
        {"key": int(k), "v": 1.0} for k in ks))
        .withOutputBatchSize(128).build())
    red = (wf.ReduceTPU_Builder(
        lambda a, b: {"key": b["key"], "v": a["v"] + b["v"]})
        .withKeyBy(lambda t: t["key"]).withName("arb").build())
    g = wf.PipeGraph("mesh_arb", wf.ExecutionMode.DEFAULT, config=cfg)
    g.add_source(src).add(red).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    g.run()
    load = g.stats()["Shard"]["per_op"]["arb"]["load"]
    assert load["placement"] == "mod" and load["n_shards"] == 8
    expected = np.bincount((ks.astype(np.int64) & 0xFFFFFFFF) % 8,
                           minlength=8)
    assert load["tuples"] == [int(c) for c in expected]
    assert load["hot_shard"] == int(expected.argmax())
    assert load["hot_keys"][0]["key"] == 9
    assert load["hot_keys"][0]["shard"] == 9 % 8


@pytest.mark.slow
def test_mesh_soak_shard_consistency():
    """Nightly leg: a longer skewed mesh run — section stays internally
    consistent (loads sum to totals, every read idempotent) across
    repeated stats reads while the graph streams."""
    g, ks = _mesh_graph()
    g.start()
    reads = 0
    while not g.is_done():
        if not g.step():
            break
        sec = g.stats()["Shard"]
        load = sec["per_op"]["mwin"].get("load")
        if load and load["total_tuples"]:
            assert sum(load["tuples"]) <= len(ks)
            reads += 1
    g.wait_end()
    final = g.stats()["Shard"]["per_op"]["mwin"]["load"]
    assert sum(final["tuples"]) == len(ks)
    assert reads > 0


# ---------------------------------------------------------------------------
# reshard advisor: plan contract + CLI
# ---------------------------------------------------------------------------

def test_reshard_plan_names_hot_shard_first(zipf_run):
    from windflow_tpu.analysis.resharding import plan
    _, sec = zipf_run
    p = plan(sec, graph_name="zipf_app")
    assert p["ops"][0]["op"] == "red"
    assert p["ops"][0]["hot_shard"] == \
        sec["per_op"]["red"]["load"]["hot_shard"]
    assert p["actionable"] >= 1
    kinds = [a["kind"] for a in p["ops"][0]["actions"]]
    # 40% of the stream on one key exceeds the mean per-shard load:
    # routing cannot fix it, the plan must say so
    assert "split_hot_key" in kinds
    assert p["ops"][0]["actions"][-1]["key"] == HOT_KEY \
        or any(a.get("key") == HOT_KEY for a in p["ops"][0]["actions"])
    json.dumps(p)


def test_reshard_plan_emits_move_override():
    """Synthetic section with medium-hot keys stacked on one shard: the
    plan moves them (key->shard override, the executor contract) and
    the projected imbalance improves."""
    from windflow_tpu.analysis.resharding import plan
    section = {
        "enabled": True,
        "per_op": {"agg": {
            "parallelism": 4, "keyed": True, "replicas": [],
            "load": {
                "n_shards": 4, "placement": "splitmix",
                "total_tuples": 4000, "batches": 10,
                "tuples": [2200, 600, 600, 600],
                "imbalance_ratio": 2.2, "hot_shard": 0, "basis": "exact",
                "hot_keys": [
                    {"key": 11, "est_tuples": 800, "share": 0.2,
                     "shard": 0},
                    {"key": 12, "est_tuples": 700, "share": 0.175,
                     "shard": 0},
                ],
                "hot_key_share": 0.2,
            },
        }},
        "totals": {},
    }
    p = plan(section, graph_name="synth")
    acts = p["ops"][0]["actions"]
    assert acts and acts[0]["kind"] == "move_keys"
    moves = acts[0]["moves"]
    assert all(m["from_shard"] == 0 for m in moves)
    assert acts[0]["override"] == {str(m["key"]): m["to_shard"]
                                   for m in moves}
    assert acts[0]["projected_imbalance_ratio"] < 2.2


def test_wf_shard_cli_round_trip(zipf_run, tmp_path):
    """tools/wf_shard.py reads a stats dump jax-free and ranks the
    seeded hot shard first with a rebalance plan (exit 0)."""
    g, _ = zipf_run
    dump = tmp_path / "stats.json"
    dump.write_text(json.dumps(g.stats(), default=str))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_shard.py"),
         "--stats", str(dump), "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert r.returncode == 0, r.stderr
    p = json.loads(r.stdout)
    assert p["ops"][0]["op"] == "red"
    assert p["ops"][0]["hot_keys"][0]["key"] == HOT_KEY
    assert p["actionable"] >= 1
    # text render names the hot shard and the plan
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_shard.py"),
         "--stats", str(dump)],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert r2.returncode == 0
    assert "hot shard" in r2.stdout and "PLAN" in r2.stdout


# ---------------------------------------------------------------------------
# surfaces: OpenMetrics, trace metadata, postmortem + wf_doctor, health
# ---------------------------------------------------------------------------

def test_openmetrics_shard_families_and_replica_labels(zipf_run):
    from windflow_tpu.monitoring.openmetrics import (parse_exposition,
                                                     render_openmetrics)
    g, sec = zipf_run
    fams = parse_exposition(render_openmetrics(g.stats()))
    # wf_shard_* families carry the SAME numbers as the section
    load = sec["per_op"]["red"]["load"]
    tuples = {labels["shard"]: v for _, labels, v
              in fams["wf_shard_tuples_total"]["samples"]
              if labels["operator"] == "red"}
    assert tuples == {str(i): float(c)
                      for i, c in enumerate(load["tuples"])}
    imb = {labels["operator"]: v for _, labels, v
           in fams["wf_shard_imbalance_ratio"]["samples"]}
    assert imb["red"] == pytest.approx(load["imbalance_ratio"])
    assert fams["wf_shard_hot_key_share"]["samples"]
    q = {labels["shard"] for _, labels, v
         in fams["wf_shard_queue_depth"]["samples"]
         if labels["operator"] == "red"}
    assert q == {"0", "1", "2", "3"}
    # per-replica collapse fixed: the per-operator counter families
    # carry one sample per replica with a `replica` label
    per_rep = [(labels["replica"], v) for _, labels, v
               in fams["wf_operator_inputs_total"]["samples"]
               if labels["operator"] == "red"]
    assert sorted(r for r, _ in per_rep) == ["0", "1", "2", "3"]
    assert sorted(v for _, v in per_rep) == sorted(
        float(c) for c in load["tuples"])


def test_shard_families_absent_when_disabled(tmp_path):
    from windflow_tpu.monitoring.openmetrics import render_openmetrics
    g = _zipf_graph(_cfg(tmp_path, shard_ledger=False), name="off_app")
    g.run()
    assert "wf_shard_" not in render_openmetrics(g.stats())


def test_dump_trace_metadata_carries_shard(zipf_run, tmp_path):
    g, _ = zipf_run
    path = g.dump_trace(str(tmp_path / "t_trace.json"))
    with open(path) as f:
        trace = json.load(f)
    shard = trace["otherData"]["shard"]
    assert shard["enabled"] is True
    assert shard["per_op"]["red"]["load"]["hot_keys"][0]["key"] == HOT_KEY


def _load_doctor():
    spec = importlib.util.spec_from_file_location(
        "wf_doctor", os.path.join(REPO, "tools", "wf_doctor.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_postmortem_shard_roundtrips_wf_doctor(zipf_run, tmp_path):
    doctor = _load_doctor()
    g, sec = zipf_run
    d = g.dump_postmortem(str(tmp_path / "bundle"), reason="shard test")
    bundle = doctor.load_bundle(d)
    doctor.validate(bundle)
    shard = bundle["sections"]["shard.json"]
    assert shard["per_op"]["red"]["load"]["tuples"] == \
        sec["per_op"]["red"]["load"]["tuples"]
    diag = doctor.diagnose(bundle)
    si = diag["shard_imbalance"]
    assert si["op"] == "red" and si["hot_key"] == HOT_KEY
    text = doctor.render_text(diag)
    assert "worst imbalance 'red'" in text
    # a corrupted shard section must fail --check, not render garbage
    spath = os.path.join(d, "shard.json")
    with open(spath) as f:
        obj = json.load(f)
    obj["per_op"]["red"]["load"]["imbalance_ratio"] = "lots"
    with open(spath, "w") as f:
        json.dump(obj, f)
    with pytest.raises(doctor.BundleError):
        doctor.validate(doctor.load_bundle(d))
    # old bundles without the section still validate (optional section)
    os.remove(spath)
    mpath = os.path.join(d, "manifest.json")
    with open(mpath) as f:
        manifest = json.load(f)
    manifest["files"].remove("shard.json")
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    doctor.validate(doctor.load_bundle(d))


def test_health_verdict_names_hot_shard(tmp_path):
    """BACKPRESSURED/STALLED attribution names the specific hot shard,
    and the stall diagnosis joins the ledger's hot-key table."""
    g = _zipf_graph(_cfg(tmp_path), name="health_shard")
    g.run()
    red = g._operators[1]
    assert red.name == "red"
    # wedge one replica: pending input on shard 2, replica alive
    red.replicas[2].inbox.append((0, object()))
    for rep in red.replicas:
        rep.done = False
    verdicts = g._health.sample()
    hs = verdicts["red"].get("hot_shard")
    assert hs and hs["shard"] == 2 and hs["queue_depth"] == 1
    diag = g._health.diagnose_stall()
    assert diag["root_cause"] == "red"
    assert diag["shard"]["hot_keys"][0]["key"] == HOT_KEY
    msg = g._health.format_diagnosis(diag)
    assert "hot shard 2" in msg
    assert f"key {HOT_KEY}" in msg
    # restore terminated state so the fixture graph stays clean
    red.replicas[2].inbox.clear()
    for rep in red.replicas:
        rep.done = True


# ---------------------------------------------------------------------------
# kill switch + overhead budget
# ---------------------------------------------------------------------------

def test_kill_switch_off_path_budget(tmp_path):
    g = _zipf_graph(_cfg(tmp_path, shard_ledger=False), name="ks_app")
    g.run()
    assert g._shard is None
    assert g.stats()["Shard"] == {"enabled": False}
    # no sketch attached anywhere: the keyed staging emitter keeps its
    # one `is not None` check per tuple and nothing else
    src = g._operators[0]
    for rep in src.replicas:
        em = rep.emitter
        assert em._sketch is None and em._sk_buf == []
    # off-path budget (mirrors the sweep ledger's): the disabled read
    # site is ONE `is not None` check — micro-assert it stays orders of
    # magnitude under a real section build
    t0 = time.perf_counter()
    for _ in range(10_000):
        g._shard_section()
    per_call = (time.perf_counter() - t0) / 10_000
    assert per_call < 5e-6, \
        f"disabled shard section costs {per_call * 1e6:.2f}us/call"


@pytest.mark.slow  # ~15s: bench.py's shard.sketch_overhead_pct guards
# the same <2% budget in every CI run (check_bench_keys hard-fails >2%),
# so this on/off A/B rides the nightly leg (wfverify-round headroom
# pass)
def test_sketch_overhead_within_budget(tmp_path_factory):
    """Overhead smoke (documented budget <2%): ledger on vs off over
    the same seeded keyed pipeline.  CPU CI timing is noisy, so the
    assertion leaves generous slack — it exists to catch a sketch that
    lands on the per-TUPLE path (orders of magnitude, not percent)."""
    ks = _zipf_keys(n=16 * 1024, seed=9)

    def run_once(enabled, i):
        cfg = _cfg(tmp_path_factory.mktemp("ovh"), shard_ledger=enabled)
        src = (wf.Source_Builder(
            lambda: iter({"key": int(k), "v": 1.0} for k in ks))
            .withOutputBatchSize(1024).withName("src").build())
        red = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": b["key"], "v": a["v"] + b["v"]})
            .withKeyBy(lambda t: t["key"]).withParallelism(2)
            .withName("red").build())
        g = wf.PipeGraph(f"ovh_{enabled}_{i}", wf.ExecutionMode.DEFAULT,
                         config=cfg)
        g.add_source(src).add(red).add_sink(
            wf.Sink_Builder(lambda t, ctx=None: None).build())
        t0 = time.perf_counter()
        g.run()
        return time.perf_counter() - t0

    run_once(True, 0)                   # warm compile caches
    on = min(run_once(True, i) for i in range(1, 4))
    off = min(run_once(False, i) for i in range(1, 4))
    assert on < off * 1.5 + 0.25, \
        f"ledger-on run {on:.3f}s vs off {off:.3f}s exceeds budget slack"
