"""Multi-chip sharding tests on the virtual 8-device CPU mesh.

Validates the ICI-collective paths (SURVEY.md §5.8 TPU-native equivalent,
BASELINE.json "keyby-sharded Reduce … linear scaling to 8 chips"): keyed
reduce via psum and via gather+fold, and FFAT window state sharded along the
key axis, against host oracles."""

import math
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from windflow_tpu.parallel import mesh as M

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402  (repo-root module: the scaling harness under test)


def _rand_batch(cap, K, seed=0):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, K, cap)
    vals = rng.integers(0, 100, cap).astype(np.float32)
    return keys, vals


def _put(mesh, payload, valid, spec):
    sh = jax.sharding.NamedSharding(mesh, spec)
    return (jax.tree.map(lambda a: jax.device_put(a, sh), payload),
            jax.device_put(valid, sh))


@pytest.mark.parametrize("data", [1, 2])
def test_sharded_keyed_reduce_psum(data):
    cap, K = 64, 16
    keys, vals = _rand_batch(cap, K)
    mesh = M.make_mesh(8, data=data)
    payload = {"k": jnp.asarray(keys, jnp.int32), "v": jnp.asarray(vals)}
    payload, valid = _put(mesh, payload, jnp.ones(cap, bool),
                          jax.sharding.PartitionSpec(("data", "key")))
    red = M.make_sharded_keyed_reduce(
        mesh, cap, K, lambda a, b: {"k": b["k"], "v": a["v"] + b["v"]},
        lambda x: x["k"], use_psum=True)
    table, has = red(payload, valid)
    expect = np.zeros(K)
    for k, v in zip(keys, vals):
        expect[k] += v
    has = np.asarray(has)
    np.testing.assert_allclose(np.asarray(table["v"])[has], expect[has],
                               rtol=1e-6)


@pytest.mark.parametrize("monoid,op,ident", [
    ("max", max, -1e30), ("min", min, 1e30)])
def test_sharded_keyed_reduce_monoid_collective(monoid, op, ident):
    """Declared max/min ride one pmax/pmin collective (r5
    withMonoidCombiner): results must match the oracle on strictly
    NEGATIVE values (a zero-identity bug would win every max), and the
    record's key leaf must survive the collective intact (max(i, i) == i
    across chips — unlike psum, where a key leaf is part of the
    declared-sum contract)."""
    cap, K = 64, 16
    keys, vals = _rand_batch(cap, K)
    vals = -1.0 - vals        # all < -1
    mesh = M.make_mesh(8, data=2)
    payload = {"k": jnp.asarray(keys, jnp.int32), "v": jnp.asarray(vals)}
    payload, valid = _put(mesh, payload, jnp.ones(cap, bool),
                          jax.sharding.PartitionSpec(("data", "key")))
    jop = jnp.maximum if monoid == "max" else jnp.minimum
    red = M.make_sharded_keyed_reduce(
        mesh, cap, K, lambda a, b: {"k": b["k"], "v": jop(a["v"], b["v"])},
        lambda x: x["k"], monoid=monoid)
    table, has = red(payload, valid)
    has = np.asarray(has)
    expect = np.full(K, ident)
    seen = np.zeros(K, bool)
    for k, v in zip(keys, vals):
        expect[k] = op(expect[k], v)
        seen[k] = True
    np.testing.assert_array_equal(has, seen)
    np.testing.assert_allclose(np.asarray(table["v"])[has], expect[has])
    np.testing.assert_array_equal(np.asarray(table["k"])[has],
                                  np.arange(K)[has])


def test_sharded_keyed_reduce_generic_fold():
    cap, K = 64, 16
    keys, vals = _rand_batch(cap, K)
    mesh = M.make_mesh(8, data=2)
    payload = {"k": jnp.asarray(keys, jnp.int32), "v": jnp.asarray(vals)}
    payload, valid = _put(mesh, payload, jnp.ones(cap, bool),
                          jax.sharding.PartitionSpec(("data", "key")))
    red = M.make_sharded_keyed_reduce(
        mesh, cap, K,
        lambda a, b: {"k": b["k"], "v": jnp.maximum(a["v"], b["v"])},
        lambda x: x["k"])
    table, has = red(payload, valid)
    has = np.asarray(has)
    expect = np.full(K, -1.0)
    seen = np.zeros(K, bool)
    for k, v in zip(keys, vals):
        expect[k] = max(expect[k], v)
        seen[k] = True
    np.testing.assert_array_equal(has, seen)
    np.testing.assert_allclose(np.asarray(table["v"])[has], expect[has])


@pytest.mark.parametrize("data,win,slide", [(1, 8, 4), (2, 8, 4), (2, 6, 2)])
def test_sharded_ffat_matches_host_oracle(data, win, slide):
    cap, K = 64, 16
    keys, vals = _rand_batch(cap, K, seed=3)
    mesh = M.make_mesh(8, data=data)
    Pn = math.gcd(win, slide)
    R, D = win // Pn, slide // Pn
    payload = {"k": jnp.asarray(keys, jnp.int32), "v": jnp.asarray(vals)}
    payload, valid = _put(mesh, payload, jnp.ones(cap, bool),
                          jax.sharding.PartitionSpec("data"))
    state = M.make_sharded_ffat_state(jnp.zeros((), jnp.float32), K, R, mesh)
    step = M.make_sharded_ffat_step(mesh, cap, K, Pn, R, D,
                                    lambda x: x["v"], lambda a, b: a + b,
                                    lambda x: x["k"])
    ts = jax.device_put(jnp.arange(cap, dtype=jnp.int64),
                        M.batch_sharding(mesh))
    # two consecutive batches to exercise the carried state across steps
    got = []
    for rep in range(2):
        state, out, fired, _ = step(state, payload, ts, valid)
        f = np.asarray(fired)
        got += list(zip(np.asarray(out["key"])[f].tolist(),
                        np.asarray(out["wid"])[f].tolist(),
                        np.asarray(out["value"])[f].tolist()))
    per_key = {}
    for _ in range(2):
        for k, v in zip(keys, vals):
            per_key.setdefault(int(k), []).append(float(v))
    exp = []
    for k, vs in per_key.items():
        for end in range(win, len(vs) + 1, slide):
            exp.append((k, (end - win) // slide, sum(vs[end - win:end])))
    got, exp = sorted(got), sorted(exp)
    assert len(got) == len(exp)
    for g, e in zip(got, exp):
        assert g[0] == e[0] and g[1] == e[1]
        assert abs(g[2] - e[2]) < 1e-3


def test_sharded_ffat_matches_single_chip():
    """The sharded program and the single-device operator program must agree
    bit-for-bit on fired windows (metamorphic: resharding must not change
    results — the §4 oracle style applied to the mesh)."""
    from windflow_tpu.windows.ffat_tpu import make_ffat_state, make_ffat_step
    cap, K, win, slide = 32, 8, 4, 2
    keys, vals = _rand_batch(cap, K, seed=7)
    Pn = math.gcd(win, slide)
    R, D = win // Pn, slide // Pn
    payload = {"k": jnp.asarray(keys, jnp.int32), "v": jnp.asarray(vals)}
    valid = jnp.ones(cap, bool)
    ts = jnp.arange(cap, dtype=jnp.int64)

    ref_state = make_ffat_state(jnp.zeros((), jnp.float32), K, R)
    ref_step = jax.jit(make_ffat_step(cap, K, Pn, R, D, lambda x: x["v"],
                                      lambda a, b: a + b, lambda x: x["k"]))
    _, rout, rfired, _ = ref_step(ref_state, payload, ts, valid)

    mesh = M.make_mesh(8, data=2)
    spayload, svalid = _put(mesh, payload, valid,
                            jax.sharding.PartitionSpec("data"))
    sstate = M.make_sharded_ffat_state(jnp.zeros((), jnp.float32), K, R, mesh)
    sstep = M.make_sharded_ffat_step(mesh, cap, K, Pn, R, D,
                                     lambda x: x["v"], lambda a, b: a + b,
                                     lambda x: x["k"])
    _, sout, sfired, _ = sstep(sstate, spayload,
                               jax.device_put(ts, M.batch_sharding(mesh)),
                               svalid)

    def fired_set(out, fired):
        f = np.asarray(fired)
        return sorted(zip(np.asarray(out["key"])[f].tolist(),
                          np.asarray(out["wid"])[f].tolist(),
                          np.asarray(out["value"])[f].tolist()))

    assert fired_set(rout, rfired) == fired_set(sout, sfired)

def test_scaling_harness_loop_body():
    """One width-2 rung of bench.py's weak-scaling harness (the per-n body
    run_bench_scaling executes on real multi-chip hardware; refused on
    virtual devices) must compose and reduce correctly — built via the
    SHARED bench.scaling_step so this test and the harness cannot drift."""
    K, per_chip = 64, 4096
    fn, payload, valid, cap = bench.scaling_step(jax, n=2, K=K,
                                                 per_chip=per_chip)
    assert cap == 2 * per_chip
    table, has = fn(payload, valid)
    exp = np.zeros(K, np.float64)
    np.add.at(exp, np.asarray(payload["k"]), np.asarray(payload["v"]))
    np.testing.assert_allclose(np.asarray(table["v"]), exp, rtol=1e-5)
    assert bool(np.asarray(has).all())


def test_scaling_harness_refuses_virtual_mesh():
    out = bench.run_bench_scaling(jax)
    assert "skipped" in out and "virtual" in out["skipped"]


def _drive_sharded_ffat_pair(comb, values, step_kwargs):
    """Shared equivalence runner: drive the key-sharded FFAT step 5 batches
    with and without the declared fast path; return both sorted firing
    lists (signature changes only need editing here)."""
    cap, K, Pn, R, D = 64, 8, 4, 4, 1
    mesh = M.make_mesh(8, data=2)
    payload = {"k": jnp.arange(cap, dtype=jnp.int32) % K, "v": values}
    ts = jnp.arange(cap, dtype=jnp.int64)
    valid = jnp.ones(cap, bool)
    sh = M.batch_sharding(mesh)
    outs = []
    for kwargs in ({}, step_kwargs):
        step = M.make_sharded_ffat_step(
            mesh, cap, K, Pn, R, D, lambda x: x["v"], comb,
            lambda x: x["k"], **kwargs)
        st = M.make_sharded_ffat_state(jnp.zeros((), jnp.int64), K, R, mesh)
        got = []
        for it in range(5):     # enough batches per key to fire windows
            p5 = {"k": jax.device_put(payload["k"], sh),
                  "v": jax.device_put(payload["v"] - it, sh)}
            st, out, fired, _ = step(st, p5, jax.device_put(ts, sh),
                                     jax.device_put(valid, sh))
            f = np.asarray(fired)
            got.extend(zip(np.asarray(out["key"])[f].tolist(),
                           np.asarray(out["wid"])[f].tolist(),
                           np.asarray(out["value"])[f].tolist()))
        outs.append(sorted(got))
    return outs


@pytest.mark.parametrize("name,comb,values,step_kwargs", [
    # flagless declared-sum fold, bitwise on integer lifts
    ("sum", lambda a, b: a + b,
     (jnp.arange(64, dtype=jnp.int64) * 3) % 101, dict(sum_like=True)),
    # declared-max scatter-combine with per-shard key bases; negative int
    # lifts — a zero-identity bug in any shard corrupts its windows
    ("max", jnp.maximum,
     -1 - ((jnp.arange(64, dtype=jnp.int64) * 7) % 89),
     dict(monoid="max")),
])
def test_sharded_ffat_declared_path_matches_default(name, comb, values,
                                                    step_kwargs):
    default, declared = _drive_sharded_ffat_pair(comb, values, step_kwargs)
    assert default == declared and default, name
