"""Device-side key compaction (windflow_tpu/parallel/compaction.py,
docs/PERF.md round 12): record-for-record A/B of the compacted dense
fast path against the sorted arbitrary-key path and the declared-dense
baseline across the reduce / stateful / FFAT-keyed families,
overflow-to-sorted correctness under adversarial key streams (all-cold,
all-hot, Zipf-shift mid-run), the pinned-table overflow contracts
(FFAT masks + counts, stateful surfaces the interner's num_key_slots
error), concurrent sibling-replica admission, the zero-extra-dispatch
pin through the jit registry, churn/hit-rate surfacing in
``stats()["Shard"]``, the remap-restore chaos cell, and the
``WF_TPU_KEY_COMPACTION`` kill-switch off-path."""

import dataclasses
import time

import jax.numpy as jnp
import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import WindFlowError, default_config
from windflow_tpu.monitoring.jit_registry import default_registry
from windflow_tpu.parallel.compaction import KEY_SENTINEL, KeyCompactor

CAP = 64


def _cfg(compact=True, **kw):
    return dataclasses.replace(default_config, key_compaction=compact,
                               **kw)


def _sink(got):
    def s(r, ctx=None):
        if r is None:
            return
        got.append(tuple(sorted((k, float(v)) for k, v in r.items()))
                   if isinstance(r, dict) else float(r))
    return wf.Sink_Builder(s).withName("snk").build()


def _run_reduce(stream, *, compact=True, monoid="max", max_keys=None,
                name="red", cap=CAP, **cfg_kw):
    got = []
    src = (wf.Source_Builder(lambda: iter(stream))
           .withOutputBatchSize(cap).withName("src").build())
    b = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": jnp.maximum(a["key"], b["key"]),
                          "v": jnp.maximum(a["v"], b["v"])})
         .withKeyBy(lambda t: t["key"]).withName(name))
    if monoid is not None:
        b = b.withMonoidCombiner(monoid)
    if max_keys is not None:
        b = b.withMaxKeys(max_keys)
    op = b.build()
    g = wf.PipeGraph("kc_reduce", wf.ExecutionMode.DEFAULT,
                     config=_cfg(compact, **cfg_kw))
    g.add_source(src).add(op).add_sink(_sink(got))
    g.run()
    return got, op, g


def _stream(n, key_of, v_of=None):
    v_of = v_of or (lambda i: -2.0 - ((i * 29) % 83) / 7.0)
    return [{"key": np.int32(key_of(i)), "v": np.float32(v_of(i))}
            for i in range(n)]


# ---------------------------------------------------------------------------
# record-for-record A/B: compacted vs sorted vs declared-dense
# ---------------------------------------------------------------------------

def test_compacted_reduce_matches_sorted_and_dense():
    """Arbitrary sparse int32 keys, declared monoid: the compacted step
    (dense slots + overflow lane, one program) must emit exactly the
    sorted path's records; the same stream remapped into [0, K) through
    the declared-dense baseline must agree too."""
    stream = _stream(512, lambda i: (i * 7) % 23 + 1000)
    compacted, op, _ = _run_reduce(stream, compact=True)
    sorted_, _, _ = _run_reduce(stream, compact=False)
    assert compacted == sorted_ and len(compacted) > 0
    s = op._compactor.summary()
    assert s["hit_rate"] == 1.0 and s["overflow_share"] == 0.0
    # declared-dense baseline over the same values, keys shifted to
    # [0, 23): per-key results must match the compacted run's
    base = _stream(512, lambda i: (i * 7) % 23)
    dense, _, _ = _run_reduce(base, compact=False, max_keys=23)
    shift = [tuple((k, v - 1000.0 if k == "key" else v) for k, v in r)
             for r in compacted]
    assert shift == dense


def test_undeclared_reduce_keeps_sorted_path():
    """No monoid declared: compaction must not attach (the dense
    scatter-combine needs the declared-monoid contract) and records
    stay the sorted path's."""
    stream = _stream(256, lambda i: (i * 11) % 19 + 500)
    a, op, _ = _run_reduce(stream, compact=True, monoid=None)
    b, _, _ = _run_reduce(stream, compact=False, monoid=None)
    assert a == b and op._compactor is None


def test_stateful_compacted_matches_interned():
    """Host-fed interning stateful: the compactor becomes the
    device-resident interner — identical records, miss-free remap."""
    def run(compact):
        got = []
        stream = _stream(512, lambda i: (i * 13) % 37 - 5,
                         v_of=lambda i: float(i))
        src = (wf.Source_Builder(lambda: iter(stream))
               .withOutputBatchSize(CAP).withName("src").build())
        op = (wf.MapTPU_Builder(
                lambda t, s: ({"key": t["key"], "v": t["v"] + s},
                              s + 1.0))
              .withInitialState(np.float32(0.0))
              .withKeyBy(lambda t: t["key"])
              .withNumKeySlots(64).withName("sm").build())
        g = wf.PipeGraph("kc_stateful", wf.ExecutionMode.DEFAULT,
                         config=_cfg(compact))
        g.add_source(src).add(op).add_sink(_sink(got))
        g.run()
        return got, op
    a, op_a = run(True)
    b, op_b = run(False)
    assert a == b and len(a) == 512
    assert op_b._compactor is None and len(op_b._interner) == 37
    s = op_a._compactor.summary()
    assert s["pinned"] and s["hit_rate"] == 1.0
    assert len(op_a._interner) == 0     # no host interning happened


def test_ffat_compacted_matches_declared_with_user_keys():
    """withCompactedKeys vs a withMaxKeys baseline whose extractor
    applies the same dense mapping by hand: same windows, same values —
    and the fired records carry the USER's keys, not remap slots, even
    when admission order scrambles the slot assignment (staggered
    arrival) and at the EOS partial-window flush."""
    def stream():
        for i in range(768):
            k = 1015 - (i * 7) % 16 if i >= 128 else 1010 + (i % 3)
            yield {"key": np.int32(k), "v": np.float32(i)}

    def run(mode):
        got = []
        src = (wf.Source_Builder(stream)
               .withOutputBatchSize(CAP).withName("src").build())
        b = wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                       lambda a, b: a + b) \
            .withCBWindows(8, 4).withName("w")
        if mode == "compact":
            b = b.withKeyBy(lambda t: t["key"]).withCompactedKeys()
        else:
            b = b.withKeyBy(lambda t: t["key"] - 1000).withMaxKeys(16)
        op = b.build()
        g = wf.PipeGraph("kc_ffat", wf.ExecutionMode.DEFAULT,
                         config=_cfg(True))
        g.add_source(src).add(op).add_sink(_sink(got))
        g.run()
        return got, op

    a, op_a = run("compact")
    b, _ = run("dense")
    norm = sorted(tuple((k, v - 1000.0 if k == "key" else v)
                        for k, v in r) for r in a)
    assert norm == sorted(b) and len(a) > 0
    assert op_a._compactor.summary()["hit_rate"] == 1.0


# ---------------------------------------------------------------------------
# adversarial key streams: the overflow lane keeps the sorted contract
# ---------------------------------------------------------------------------

def test_all_cold_stream_overflows_to_sorted():
    """Distinct keys far beyond the slot budget: nearly every lane
    misses, the full-width sorted fallback (lax.cond big path) runs,
    and records still match the sorted path exactly."""
    stream = _stream(2048, lambda i: i * 3 + 7)
    a, op, _ = _run_reduce(stream, compact=True, cap=128,
                           key_compaction_slots=32)
    b, _, _ = _run_reduce(stream, compact=False, cap=128)
    assert a == b and len(a) == 2048
    s = op._compactor.summary()
    assert s["big_fallbacks"] > 0 and s["overflow_share"] > 0.9


def test_all_hot_stream_stays_dense():
    """Key cardinality under the slot budget: everything admits at the
    staging boundary, zero overflow, zero churn."""
    stream = _stream(1024, lambda i: (i % 8) * 1000)
    a, op, _ = _run_reduce(stream, compact=True)
    b, _, _ = _run_reduce(stream, compact=False)
    assert a == b
    s = op._compactor.summary()
    assert s["hit_rate"] == 1.0 and s["churn"] == 0
    assert s["big_fallbacks"] == 0


def test_zipf_shift_mid_run_reseeds_and_churns():
    """Hot set shifts mid-stream on a FULL table: the reseed cadence
    folds the shard sketch's new hot candidates in, evicting provably
    colder slots (the churn counter) — records equal the sorted path
    throughout the shift."""
    def key_of(i):
        if i < 1024:
            return 100 + i % 16          # fills the 16-slot table
        return 9000 + i % 4 if i % 8 else 100 + i % 16

    stream = _stream(4096, key_of)
    a, op, _ = _run_reduce(stream, compact=True, cap=128,
                           key_compaction_slots=16,
                           key_compaction_reseed=4)
    b, _, _ = _run_reduce(stream, compact=False, cap=128)
    assert a == b
    s = op._compactor.summary()
    assert s["reseeds"] > 0
    assert s["churn"] > 0, s
    assert op._compactor.slot_of(9000) is not None   # new hot key seated


def test_sentinel_key_rides_overflow_lane():
    """A record keyed exactly INT32_MAX (the table sentinel) is never
    admitted and never wrong: it rides the sorted overflow lane."""
    stream = _stream(128, lambda i: 2**31 - 1 if i % 16 == 0 else i % 5)
    a, op, _ = _run_reduce(stream, compact=True)
    b, _, _ = _run_reduce(stream, compact=False)
    assert a == b
    assert op._compactor.slot_of(int(KEY_SENTINEL)) is None
    assert op._compactor.summary()["overflow_tuples"] > 0


def test_sentinel_key_deactivates_stateful_to_intern():
    """The stateful plane has a lossless intern fallback: a sentinel
    user key deactivates the compactor (instead of dropping the record)
    and the run matches plain interning."""
    def run(compact):
        got = []
        stream = _stream(256, lambda i: 2**31 - 1 if i == 40 else i % 9,
                         v_of=lambda i: float(i))
        src = (wf.Source_Builder(lambda: iter(stream))
               .withOutputBatchSize(CAP).withName("src").build())
        op = (wf.MapTPU_Builder(
                lambda t, s: ({"key": t["key"], "v": t["v"] + s},
                              s + 1.0))
              .withInitialState(np.float32(0.0))
              .withKeyBy(lambda t: t["key"])
              .withNumKeySlots(32).withName("sm").build())
        g = wf.PipeGraph("kc_sentinel", wf.ExecutionMode.DEFAULT,
                         config=_cfg(compact))
        g.add_source(src).add(op).add_sink(_sink(got))
        g.run()
        return got, op
    a, op_a = run(True)
    b, _ = run(False)
    assert a == b and len(a) == 256     # the sentinel record survived
    assert op_a._compactor is None or not op_a._compactor.active


def test_ffat_slot_overflow_masks_and_counts():
    """More distinct keys than the pinned slot budget: the table keeps
    serving the admitted keys (no deactivation, no error — the
    operator's documented out-of-range contract), the rejected keys'
    lanes are masked invalid and counted (``full_rejects`` + the miss
    counters), and the admitted keys' windows still match a
    declared-dense run over the stream filtered to those keys."""
    stream = [{"key": np.int32(i % 8), "v": np.float32(i)}
              for i in range(512)]

    def run(records, mode):
        got = []
        src = (wf.Source_Builder(lambda: iter(records))
               .withOutputBatchSize(CAP).withName("src").build())
        b = wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                       lambda a, b: a + b) \
            .withCBWindows(8, 4).withKeyBy(lambda t: t["key"]) \
            .withName("w")
        b = (b.withCompactedKeys() if mode == "compact"
             else b.withMaxKeys(8))
        op = b.build()
        g = wf.PipeGraph("kc_full", wf.ExecutionMode.DEFAULT,
                         config=_cfg(True, key_compaction_slots=4))
        g.add_source(src).add(op).add_sink(_sink(got))
        g.run()
        return got, op

    a, op = run(stream, "compact")
    s = op._compactor.summary()
    assert s["full_rejects"] > 0 and "deactivated" not in s
    assert 0.0 < s["hit_rate"] < 1.0
    admitted = {k for k in range(8)
                if op._compactor.slot_of(k) is not None}
    assert len(admitted) == 4
    base, _ = run([r for r in stream if int(r["key"]) in admitted],
                  "dense")
    assert sorted(a) == sorted(base) and len(a) > 0


def test_stateful_slot_overflow_raises_interner_error():
    """Distinct keys beyond num_key_slots on the pinned intern-fallback
    compactor: the overflow surfaces as the interner's num_key_slots
    error on that very batch — the admission path deactivates to the
    lossless host interner instead of swallowing the overflow into
    silently masked records."""
    stream = _stream(256, lambda i: i % 12, v_of=lambda i: float(i))
    src = (wf.Source_Builder(lambda: iter(stream))
           .withOutputBatchSize(CAP).withName("src").build())
    op = (wf.MapTPU_Builder(
            lambda t, s: ({"key": t["key"], "v": t["v"] + s}, s + 1.0))
          .withInitialState(np.float32(0.0))
          .withKeyBy(lambda t: t["key"])
          .withNumKeySlots(8).withName("sm").build())
    g = wf.PipeGraph("kc_overflow", wf.ExecutionMode.DEFAULT,
                     config=_cfg(True))
    g.add_source(src).add(op).add_sink(_sink([]))
    with pytest.raises(WindFlowError, match="num_key_slots"):
        g.run()


def test_ffat_dead_admission_path_fails_loudly():
    """A compacted window has NO lossless fallback: if the host
    admission path dies (speculative probe failure / admission
    anomaly), the next dispatch raises with the withMaxKeys hint
    instead of silently masking every not-yet-admitted key's records
    forever."""
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                     lambda a, b: a + b)
          .withCBWindows(8, 4).withKeyBy(lambda t: t["key"])
          .withCompactedKeys().withName("w").build())

    def gen():
        # runs after the graph build attached the compactor, before
        # the first batch ships — the probe-failure state
        op._compactor.deactivate()
        for i in range(256):
            yield {"key": np.int32(i % 4), "v": np.float32(i)}

    src = (wf.Source_Builder(gen)
           .withOutputBatchSize(CAP).withName("src").build())
    g = wf.PipeGraph("kc_dead", wf.ExecutionMode.DEFAULT,
                     config=_cfg(True))
    g.add_source(src).add(op).add_sink(_sink([]))
    with pytest.raises(WindFlowError, match="admission"):
        g.run()


def test_stateful_restore_across_kill_switch():
    """The remap is the key→slot half of per-key state: a compacted
    checkpoint restored with the plane OFF folds the mapping into the
    host interner (rows keep meaning the same keys — no silent
    re-intern-from-slot-0 corruption), and an interned checkpoint
    restored with the plane ON keeps the interner path (a fresh remap
    would assign conflicting slots)."""
    def run(compact):
        got = []
        stream = _stream(256, lambda i: (i * 13) % 37 - 5,
                         v_of=lambda i: float(i))
        src = (wf.Source_Builder(lambda: iter(stream))
               .withOutputBatchSize(CAP).withName("src").build())
        op = (wf.MapTPU_Builder(
                lambda t, s: ({"key": t["key"], "v": t["v"] + s},
                              s + 1.0))
              .withInitialState(np.float32(0.0))
              .withKeyBy(lambda t: t["key"])
              .withNumKeySlots(64).withName("sm").build())
        g = wf.PipeGraph("kc_xkill", wf.ExecutionMode.DEFAULT,
                         config=_cfg(compact))
        g.add_source(src).add(op).add_sink(_sink(got))
        g.run()
        return op

    op_a = run(True)            # compacted run
    op_b = run(False)           # interned run
    blob_a = op_a.snapshot_state()
    blob_b = op_b.snapshot_state()
    # compacted checkpoint -> plane-off operator: mapping adopted
    op_b.restore_state(blob_a)
    assert op_b._interner._ids == op_a._compactor.export_mapping()
    # interned checkpoint -> compacted operator: interner owns the rows
    assert op_a._compactor is not None
    op_a.restore_state(blob_b)
    assert op_a._compactor is None
    assert op_a._interner._ids == blob_b["interner"]


def test_concurrent_admission_keeps_table_consistent():
    """Sibling host emitter replicas drain on the worker pool and admit
    into ONE consumer's compactor concurrently: admission, rebuild and
    the table/placement reads hold the lock, so the sorted key mirror,
    the slot mirror and the dict stay mutually consistent (regression:
    dict-changed-size mid ``_rebuild`` / torn ``(_tk, _tsl)`` pairs /
    double-popped free slots)."""
    import threading

    comp = KeyCompactor(256, name="hammer")
    errs = []

    def worker(seed):
        rng = np.random.RandomState(seed)
        try:
            for _ in range(200):
                comp.observe(rng.randint(0, 300, 32).astype(np.int64))
                comp.place_np(rng.randint(0, 300, 16).astype(np.int64),
                              4)
        except Exception as e:      # noqa: BLE001 — the regression
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(s,)) for s in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert errs == []
    n = len(comp._key_slot)
    keys = np.sort(np.fromiter(comp._key_slot.keys(), np.int32,
                               count=n))
    assert np.array_equal(keys, comp._tk[:n])
    for k, slot in comp._key_slot.items():
        pos = int(np.searchsorted(comp._tk[:n], np.int32(k)))
        assert comp._tsl[pos] == slot
    # every slot accounted for exactly once: occupied + free partition
    assert sorted(list(comp._key_slot.values())
                  + list(comp._free)) == list(range(256))


# ---------------------------------------------------------------------------
# the bounded (withMaxKeys) reroute: the PR 1 drop path retired
# ---------------------------------------------------------------------------

def test_bounded_reduce_reroutes_out_of_range_instead_of_dropping():
    """withMaxKeys + monoid with out-of-range keys: compaction routes
    them down the overflow/sorted lane (kept, counted) — the records
    equal the UNDECLARED sorted path's, and no RuntimeWarning fires."""
    import warnings
    stream = _stream(320, lambda i: i % 10)
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        a, op, _ = _run_reduce(stream, compact=True, max_keys=6)
    b, _, _ = _run_reduce(stream, compact=False, monoid=None)
    assert a == b                       # out-of-range keys KEPT
    st = op.dump_stats()
    n_oor = sum(1 for t in stream if t["key"] >= 6)
    assert st["Out_of_range_keys_rerouted"] == n_oor
    assert "Out_of_range_keys_dropped" not in st
    assert op._compactor.bounded


# ---------------------------------------------------------------------------
# zero extra dispatches + stats surfacing
# ---------------------------------------------------------------------------

def test_zero_extra_dispatch_per_batch():
    """The remap rides the consumer's ONE program (tables are read-only
    operands, cstats is donated): the jit registry shows exactly one
    dispatch per batch for the hop and no second remap program."""
    default_registry().reset()
    stream = _stream(512, lambda i: (i * 7) % 23 + 1000)
    _, op, _ = _run_reduce(stream, compact=True, name="zed")
    snap = default_registry().snapshot()
    assert snap["zed.compact"]["dispatches"] == 512 // CAP
    others = [k for k in snap if k.startswith("zed") and
              k != "zed.compact" and snap[k]["dispatches"]]
    assert others == [], f"extra programs dispatched: {others}"
    assert snap["zed.compact"]["recompiles"] == 0


def test_stats_shard_section_carries_compaction():
    """stats()["Shard"].per_op.<op>.compaction surfaces hit rate /
    overflow share / churn beside the load sketch, and dump_stats
    carries the same summary."""
    stream = _stream(512, lambda i: (i * 7) % 23 + 1000)
    _, op, g = _run_reduce(stream, compact=True)
    sec = g.stats()["Shard"]["per_op"][op.name]["compaction"]
    assert sec["hit_rate"] == 1.0
    assert sec["tuples"] == 512
    assert {"slots", "occupied", "overflow_share", "churn",
            "churn_per_sweep", "reseeds"} <= set(sec)
    assert op.dump_stats()["Key_compaction"]["tuples"] == 512


# ---------------------------------------------------------------------------
# durable state: the remap restores exactly (kill -> restore -> diff)
# ---------------------------------------------------------------------------

def test_chaos_remap_restores_record_for_record(tmp_path):
    """window_compact chaos cell: the compacted FFAT's pane rings index
    by remap slots, so a replay under a different key->slot assignment
    would emit wrong keys — the kill -> restore -> diff proves the
    remap snapshot restores bit-exactly through the epoch protocol."""
    from windflow_tpu.durability import chaos
    base = chaos.make_cell("window_compact", str(tmp_path / "ck_a"))
    chal = chaos.make_cell("window_compact", str(tmp_path / "ck_b"))
    v = chaos.run_ab(base["factory"], chal["factory"],
                     chaos.default_kill("window_compact", "mid_epoch"),
                     base["read"], chal["read"])
    assert v["diff"] is None, v["diff"]
    assert v["restored_epoch"] is not None
    assert v["records"] > 0


def test_compactor_snapshot_round_trip():
    """Unit: snapshot/restore reproduces the key->slot table, the free
    list, and the cadence counters on a fresh instance."""
    c = KeyCompactor(8, reseed_every=4, name="u")
    c.observe(np.array([5, 9, 5, 130], np.int64))
    c.on_batch()
    blob = c.snapshot()
    r = KeyCompactor(8, reseed_every=4, name="u")
    r.restore(blob)
    assert r.slot_of(5) == c.slot_of(5)
    assert r.slot_of(130) == c.slot_of(130)
    assert sorted(r._free) == sorted(c._free)
    assert np.array_equal(r._tk, c._tk)
    assert np.array_equal(r._tsl, c._tsl)


# ---------------------------------------------------------------------------
# kill switch: off-path is one `is not None` check
# ---------------------------------------------------------------------------

def test_kill_switch_attaches_nothing():
    stream = _stream(256, lambda i: (i * 7) % 23 + 1000)
    _, op, g = _run_reduce(stream, compact=False)
    assert op._compactor is None and op._cstats is None
    for o in g._operators:
        assert o._compactor is None
        for rep in o.replicas:
            em = rep.emitter
            if em is not None:
                assert getattr(em, "_compactor", None) is None
    assert "Key_compaction" not in op.dump_stats()
    assert "compaction" not in g.stats()["Shard"]["per_op"][op.name]
    # off-path budget: the disabled stats read is one attribute check —
    # micro-assert it stays orders of magnitude under a summary build
    t0 = time.perf_counter()
    for _ in range(10_000):
        op._compactor is not None
    per_call = (time.perf_counter() - t0) / 10_000
    assert per_call < 1e-6


def test_ffat_compacted_keys_require_plane():
    """withCompactedKeys under WF_TPU_KEY_COMPACTION=0 fails loudly at
    the first batch with the declare-withMaxKeys hint."""
    src = (wf.Source_Builder(
        lambda: iter([{"key": np.int32(5), "v": np.float32(1.0)}] * 64))
        .withOutputBatchSize(32).withName("src").build())
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                     lambda a, b: a + b)
          .withCBWindows(8, 4).withKeyBy(lambda t: t["key"])
          .withCompactedKeys().withName("w").build())
    g = wf.PipeGraph("kc_kill", wf.ExecutionMode.DEFAULT,
                     config=_cfg(False))
    g.add_source(src).add(op).add_sink(_sink([]))
    with pytest.raises(WindFlowError, match="withMaxKeys"):
        g.run()


# ---------------------------------------------------------------------------
# preflight: WF404 advice + the WF402 compacted-mesh extension
# ---------------------------------------------------------------------------

def test_preflight_wf404_bounded_without_monoid():
    def graph(declare):
        src = (wf.Source_Builder(lambda: iter([{"key": np.int32(1),
                                                "v": np.float32(1.0)}]))
               .withOutputBatchSize(8)
               .withRecordSpec({"key": np.int32(0),
                                "v": np.float32(0.0)})
               .withName("src").build())
        b = (wf.ReduceTPU_Builder(
                lambda a, b: {"key": a["key"], "v": a["v"] + b["v"]})
             .withKeyBy(lambda t: t["key"]).withMaxKeys(8)
             .withName("red"))
        if declare:
            b = b.withSumCombiner()
        g = wf.PipeGraph("kc_wf404", wf.ExecutionMode.DEFAULT,
                         config=_cfg(True))
        g.add_source(src).add(b.build()).add_sink(_sink([]))
        return g

    assert any(d.code == "WF404" for d in graph(False).check())
    # declared monoid: the advice disappears
    assert not any(d.code == "WF404" for d in graph(True).check())


def test_preflight_wf405_monoid_comb_divergence():
    """WF405: the declared kind REPLACES the combiner on the dense/
    compacted stages, so a combiner that provably diverges from it
    leafwise must be flagged — with compaction default-on, the natural
    ``{"key": a["key"], ...}`` idiom under a declared "sum" silently
    emits key*count for every admitted key (found live by the e2e
    verify harness)."""
    def graph(comb, monoid):
        src = (wf.Source_Builder(lambda: iter([{"key": np.int32(1),
                                                "v": np.float32(1.0)}]))
               .withOutputBatchSize(8)
               .withRecordSpec({"key": np.int32(0),
                                "v": np.float32(0.0)})
               .withName("src").build())
        op = (wf.ReduceTPU_Builder(comb)
              .withKeyBy(lambda t: t["key"])
              .withMonoidCombiner(monoid).withName("red").build())
        g = wf.PipeGraph("kc_wf405", wf.ExecutionMode.DEFAULT,
                         config=_cfg(True))
        g.add_source(src).add(op).add_sink(_sink([]))
        return g

    def codes(g):
        return [d.code for d in g.check()]

    # key passthrough under "sum": the dense scatter ADDS equal keys
    d = [x for x in graph(
        lambda a, b: {"key": a["key"], "v": a["v"] + b["v"]},
        "sum").check() if x.code == "WF405"]
    assert len(d) == 1 and "'key'" in d[0].message
    # same passthrough under idempotent "max" is the blessed idiom
    assert "WF405" not in codes(graph(
        lambda a, b: {"key": a["key"], "v": jnp.maximum(a["v"], b["v"])},
        "max"))
    # recognized monoid primitive of the WRONG kind on a value leaf
    assert "WF405" in codes(graph(
        lambda a, b: {"key": a["key"] + b["key"],
                      "v": jnp.maximum(a["v"], b["v"])}, "sum"))
    # fully matching leafwise combiners stay silent
    assert "WF405" not in codes(graph(
        lambda a, b: {"key": jnp.maximum(a["key"], b["key"]),
                      "v": jnp.maximum(a["v"], b["v"])}, "max"))
    assert "WF405" not in codes(graph(
        lambda a, b: {"key": a["key"] + b["key"], "v": a["v"] + b["v"]},
        "sum"))
    # inconclusive structure (where-based max) never false-positives
    assert "WF405" not in codes(graph(
        lambda a, b: {"key": a["key"],
                      "v": jnp.where(a["v"] > b["v"], a["v"], b["v"])},
        "max"))
    # key copied into a VALUE leaf is not the blessed idiom: output 'v'
    # diverges under the declared max even though the SOURCE is the key
    src = (wf.Source_Builder(lambda: iter([{"key": np.int32(1),
                                            "v": np.int32(1)}]))
           .withOutputBatchSize(8)
           .withRecordSpec({"key": np.int32(0), "v": np.int32(0)})
           .withName("src").build())
    op = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": jnp.maximum(a["key"], b["key"]),
                          "v": a["key"]})
          .withKeyBy(lambda t: t["key"])
          .withMonoidCombiner("max").withName("red").build())
    g = wf.PipeGraph("kc_wf405_xleaf", wf.ExecutionMode.DEFAULT,
                     config=_cfg(True))
    g.add_source(src).add(op).add_sink(_sink([]))
    d = [x for x in g.check() if x.code == "WF405"]
    assert len(d) == 1 and "'v'" in d[0].message


# ---------------------------------------------------------------------------
# KeyCompactor unit contracts: reseed cost bound + reserved-key counter
# ---------------------------------------------------------------------------

def test_reseed_one_estimation_pass():
    """Eviction during one reseed pays ONE sketch-estimation pass over
    the residents (coldest-first walk), not one full rescan per
    admitted candidate — the O(slots^2) stall this pins down ran
    inline on the consumer step path."""
    from windflow_tpu.parallel.compaction import KeyCompactor

    class Sketch:
        def __init__(self):
            self.calls = 0
            # resident coldness: key k has weight k (1..4 resident)
            self.hot = [(100 + i, 1000 - i) for i in range(4)]

        def hot_candidates(self, limit):
            return self.hot[:limit]

        def _estimate(self, k):
            self.calls += 1
            return int(k)

    comp = KeyCompactor(4, reseed_every=1, name="reseed_cost")
    comp.observe(np.arange(1, 5, dtype=np.int64))   # fill: keys 1..4
    sk = Sketch()
    comp.bind_sketch(sk)
    comp.reseed()
    # all four hot candidates (est ~1000) clear 2x vs residents 1..4
    assert comp.churn == 4
    assert set(comp._key_slot) == {100, 101, 102, 103}
    # ONE pass over the 4 residents, not 4 candidates x 4 residents
    assert sk.calls == 4


def test_packed_min_liveness_at_ts_floor():
    """Packed "min" scatter: the ts column rides NEGATED with identity
    I64MAX, and -(I64MIN+1) == I64MAX — a lane ts at the int64 floor
    must not read its row back as dead (record silently dropped vs the
    sorted path's bit-identical contract)."""
    from windflow_tpu.parallel import compaction
    cap, T = 8, 4
    body = compaction.make_compacted_reduce(
        cap, T, "min",
        lambda a, b: {"v": jnp.minimum(a["v"], b["v"])},
        None, None, True)
    i64min = np.iinfo(np.int64).min
    keys = jnp.asarray([0, 1, 2, 3, 0, 1, 2, 3], jnp.int32)
    payload = {"v": jnp.asarray(np.arange(8), jnp.float32)}
    valid = jnp.ones(cap, bool)
    for floor_ts in (i64min, i64min + 1):
        ts = jnp.full(cap, floor_ts, jnp.int64)
        out_p, out_ts, out_valid, _ = body(keys, payload, ts, valid,
                                           compaction.cstats_init())
        assert int(jnp.sum(out_valid)) == 4
        np.testing.assert_allclose(
            np.asarray(out_p["v"])[:4], [0.0, 1.0, 2.0, 3.0])


def test_observe_one_lock_free_on_full_table():
    """A full evictable table must not serialize the per-tuple emit
    path on the compactor lock: cold keys are counted (full_rejects)
    without admission, and a held lock cannot block the read."""
    from windflow_tpu.parallel.compaction import KEY_SENTINEL, KeyCompactor
    comp = KeyCompactor(2, name="full_fast")
    comp.observe(np.asarray([1, 2], np.int64))
    assert not comp._free
    with comp._lock:           # would deadlock if the path locked
        comp.observe_one(99)
        comp.observe_one(int(KEY_SENTINEL))
    assert comp.slot_of(99) is None
    s = comp.summary()
    assert s["full_rejects"] == 1 and s["sentinel_rejects"] == 1


def test_sentinel_key_counted_not_silent():
    """A real key equal to the INT32_MAX table sentinel is never
    admitted, and the encounter is COUNTED (sentinel_rejects) instead
    of vanishing into generic overflow."""
    from windflow_tpu.parallel.compaction import KEY_SENTINEL, KeyCompactor
    comp = KeyCompactor(4, name="sentinel")
    comp.observe(np.asarray([int(KEY_SENTINEL), 7], np.int64))
    assert comp.slot_of(7) is not None
    assert comp.slot_of(int(KEY_SENTINEL)) is None
    assert comp.summary()["sentinel_rejects"] == 1
