"""Dashboard server tests: a traced graph registers over TCP and its
reports become visible over HTTP (end-to-end counterpart of the reference's
dashboard protocol + REST surface)."""

import dataclasses
import json
import urllib.request

import windflow_tpu as wf
from windflow_tpu.basic import default_config
from windflow_tpu.monitoring import DashboardServer


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_dashboard_end_to_end():
    server = DashboardServer(tcp_port=0, http_port=0).start()
    try:
        cfg = dataclasses.replace(default_config, tracing_enabled=True,
                                  dashboard_host="127.0.0.1",
                                  dashboard_port=server.tcp_port)
        src = (wf.Source_Builder(
            lambda: iter({"k": i % 3, "v": i} for i in range(2000)))
            .withName("src").build())
        snk = wf.Sink_Builder(lambda t, ctx=None: None).withName("snk").build()
        g = wf.PipeGraph("dash_app", wf.ExecutionMode.DEFAULT, config=cfg)
        g.add_source(src).add_sink(snk)
        g.run()

        status, body = _get(server.http_port, "/apps")
        assert status == 200
        apps = json.loads(body)
        assert len(apps) == 1
        app = apps[0]
        assert app["name"] == "dash_app"
        assert app["alive"] is False        # END_APP received
        assert app["num_reports"] >= 1

        status, body = _get(server.http_port, f"/apps/{app['id']}/latest")
        report = json.loads(body)
        assert report["PipeGraph_name"] == "dash_app"
        assert report["Operator_number"] == 2

        status, body = _get(server.http_port, f"/apps/{app['id']}/diagram")
        assert status == 200
        assert b"svg" in body[:200].lower() or body[:1] == b"<"

        status, _ = _get(server.http_port, "/apps/999")
        assert status == 404
    finally:
        server.stop()


def test_dashboard_multiple_apps():
    server = DashboardServer(tcp_port=0, http_port=0).start()
    try:
        for name in ("app_a", "app_b"):
            cfg = dataclasses.replace(default_config, tracing_enabled=True,
                                      dashboard_host="127.0.0.1",
                                      dashboard_port=server.tcp_port)
            src = (wf.Source_Builder(lambda: iter(range(100)))
                   .withName("s").build())
            snk = wf.Sink_Builder(lambda t, ctx=None: None).build()
            g = wf.PipeGraph(name, wf.ExecutionMode.DEFAULT, config=cfg)
            g.add_source(src).add_sink(snk)
            g.run()
        _, body = _get(server.http_port, "/apps")
        apps = json.loads(body)
        assert sorted(a["name"] for a in apps) == ["app_a", "app_b"]
        assert [a["id"] for a in apps] == sorted(a["id"] for a in apps)
    finally:
        server.stop()


def test_dashboard_serves_spa():
    """GET / returns the single-page UI (reference React SPA equivalent):
    static HTML polling the JSON endpoints."""
    server = DashboardServer(tcp_port=0, http_port=0).start()
    try:
        status, body = _get(server.http_port, "/")
        assert status == 200
        html = body.decode()
        assert "<html" in html
        assert "/apps" in html          # it polls the JSON API
        assert "spark" in html          # throughput sparklines
        status2, body2 = _get(server.http_port, "/index.html")
        assert status2 == 200 and body2 == body
    finally:
        server.stop()
