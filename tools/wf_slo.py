#!/usr/bin/env python
"""wf_slo: rank latency budget share and emit an adaptive-sizing plan.

CLI face of the latency advisor (windflow_tpu/analysis/latency.py),
mirroring ``tools/wf_shard.py``: point it at a stats dump carrying a
``Latency_plane`` section (a ``dump_stats`` JSON, a postmortem
``stats.json`` / ``latency.json``, or a bare section file) and get
every operator ranked by its share of the decomposed critical path,
the dominant segment behind that share, and the concrete per-operator
``megastep_sweeps``/tick-chunk overrides the PR-18 adaptive sizer
implements (``plan(...)`` is that executor's contract, exactly as
``wf_shard.plan`` was the reshard executor's).

Usage::

    python tools/wf_slo.py --stats DUMP          # rank + plan
    python tools/wf_slo.py ... --json            # machine-readable
    python tools/wf_slo.py ... --top N           # worst N ops only
    python tools/wf_slo.py --check --stats DUMP  # SLO gate: exit 1
        # while the dump's latched SLO_VIOLATED verdict is active

This tool never imports jax (the ``wf_metrics``/``wf_doctor``
scrape-host stance — the advisor module is loaded file-direct, skipping
the package __init__).  Exit status: 0 when the plan has at least one
action (or --check passes), 1 when there is nothing to do (or --check
finds the SLO violated), 2 on usage/load failures.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_advisor():
    """File-direct import of analysis/latency.py (pure stdlib): skips
    the ``windflow_tpu`` package __init__, which imports jax."""
    path = os.path.join(REPO, "windflow_tpu", "analysis", "latency.py")
    spec = importlib.util.spec_from_file_location("_wf_latency", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def fail(msg: str) -> None:
    print(f"wf_slo: FAIL: {msg}", file=sys.stderr)
    sys.exit(2)


def load_latency_section(path: str) -> dict:
    """The ``Latency_plane`` section out of a stats dump / postmortem
    stats.json / bare latency.json file."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        fail(f"cannot read stats dump '{path}': {e}")
    if isinstance(obj, dict) and "segments_total_usec" in obj:
        return obj                     # bare latency.json section
    lat = (obj or {}).get("Latency_plane")
    if not isinstance(lat, dict) or not lat.get("enabled"):
        fail(f"'{path}' carries no enabled 'Latency_plane' section — "
             "run the graph with Config.flight_recorder and "
             "Config.latency_ledger on and dump_stats first")
    return lat


def render_text(p: dict) -> str:
    budget = p.get("slo_budget_ms")
    head = (f"e2e p99 {p['e2e_p99_ms']} ms vs budget {budget} ms "
            f"({'OVER' if p['over_budget'] else 'within'})"
            if budget else
            f"e2e p99 {p['e2e_p99_ms']} ms (no SLO declared)")
    lines = [f"wf_slo: graph '{p.get('graph') or '?'}' — {head}; "
             f"{p['actionable']} operator(s) with actions"]
    v = p.get("verdict")
    if v:
        tag = "ACTIVE" if p.get("slo_active") else "last"
        lines.append(f"  verdict ({tag}): {v.get('message')}")
    for i, o in enumerate(p["ops"], 1):
        share = o.get("budget_share")
        lines.append(
            f"  #{i} {o['op']}: "
            f"{'?' if share is None else f'{share:.0%}'} of the "
            f"critical path, dominant {o.get('dominant_segment') or '?'}"
            + (f", megastep K={o['megastep_k']}"
               + (f" (freshness floor "
                  f"{o['freshness_floor_usec']} µs)"
                  if o.get("freshness_floor_usec") is not None else "")
               if o.get("megastep_k") else ""))
        for a in o["actions"]:
            if a["kind"] in ("set_megastep_sweeps",
                             "regrow_megastep_sweeps"):
                lines.append(
                    f"      PLAN {a['kind']} {a['from_k']}→"
                    f"{a['recommended_k']} — {a['note']}")
            elif a["kind"] == "shrink_tick_chunk":
                lines.append(
                    f"      PLAN shrink_tick_chunk /"
                    f"{a['shrink_factor']} — {a['note']}")
        if not o["actions"]:
            lines.append("      (no action)")
    if not p["ops"]:
        lines.append("  (no decomposed traces yet — is the flight "
                     "recorder sampling and the graph running?)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--stats", metavar="DUMP", required=True,
                    help="stats JSON with a Latency_plane section "
                         "(dump_stats output, postmortem stats.json, "
                         "or a bare latency.json)")
    ap.add_argument("--json", action="store_true",
                    help="emit the ranked plan as JSON")
    ap.add_argument("--top", type=int, default=0,
                    help="emit only the worst N operators")
    ap.add_argument("--check", action="store_true",
                    help="SLO gate: exit 1 while the dump's latched "
                         "violation verdict is active")
    args = ap.parse_args(argv)

    lat = load_latency_section(args.stats)
    adv = _load_advisor()
    p = adv.plan(lat, top=args.top)
    if args.check:
        if p["slo_active"]:
            v = p.get("verdict") or {}
            print(f"wf_slo: SLO VIOLATED — {v.get('message', '?')}")
            return 1
        print(f"wf_slo: OK — e2e p99 {p['e2e_p99_ms']} ms"
              + (f" within budget {p['slo_budget_ms']} ms"
                 if p.get("slo_budget_ms") else " (no SLO declared)"))
        return 0
    if args.json:
        print(json.dumps(p, indent=2))
    else:
        print(render_text(p))
    return 0 if p["actionable"] else 1


if __name__ == "__main__":
    sys.exit(main())
