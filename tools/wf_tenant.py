#!/usr/bin/env python
"""wf_tenant: rank tenants by budget pressure and emit a scheduler plan.

CLI face of the tenancy advisor (windflow_tpu/analysis/tenancy.py),
mirroring ``tools/wf_slo.py``/``tools/wf_shard.py``: point it at a
stats dump carrying a ``Tenant`` section (a ``dump_stats`` JSON, a
postmortem ``stats.json`` / ``tenant.json``, or a bare section file)
and get every tenant in the process ranked by HBM budget pressure,
with the concrete ``throttle_admission``/``rescale_tenant``/
``drain_shards``/``rebalance_hot_tenant`` actions the PR-20 tenant
scheduler executes (``plan(...)`` is that executor's contract, exactly
as ``wf_shard.plan`` was the reshard executor's).

Usage::

    python tools/wf_tenant.py --stats DUMP          # rank + plan
    python tools/wf_tenant.py ... --json            # machine-readable
    python tools/wf_tenant.py ... --top N           # worst N tenants
    python tools/wf_tenant.py --check --stats DUMP  # budget gate:
        # exit 1 while any tenant's latched OVER_BUDGET verdict is
        # active, or the attributed staged fraction is under
        # --min-fraction (default 0.9, the CI reconciliation floor)

This tool never imports jax (the ``wf_metrics``/``wf_doctor``
scrape-host stance — the advisor module is loaded file-direct, skipping
the package __init__).  Exit status: 0 when the plan has at least one
action (or --check passes), 1 when there is nothing to do (or --check
fails), 2 on usage/load failures.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_advisor():
    """File-direct import of analysis/tenancy.py (pure stdlib): skips
    the ``windflow_tpu`` package __init__, which imports jax."""
    path = os.path.join(REPO, "windflow_tpu", "analysis", "tenancy.py")
    spec = importlib.util.spec_from_file_location("_wf_tenancy", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def fail(msg: str) -> None:
    print(f"wf_tenant: FAIL: {msg}", file=sys.stderr)
    sys.exit(2)


def load_tenant_section(path: str) -> dict:
    """The ``Tenant`` section out of a stats dump / postmortem
    stats.json / bare tenant.json file."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        fail(f"cannot read stats dump '{path}': {e}")
    if isinstance(obj, dict) and "tenants" in obj:
        return obj                     # bare tenant.json section
    ten = (obj or {}).get("Tenant")
    if not isinstance(ten, dict) or not ten.get("enabled"):
        fail(f"'{path}' carries no enabled 'Tenant' section — run the "
             "graph with Config.tenant_ledger on and dump_stats first")
    return ten


def _bar(pressure, width: int = 20) -> str:
    """ASCII budget bar: filled to min(pressure, 1), '!' past 1."""
    if pressure is None:
        return "(no budget)"
    fill = min(1.0, pressure)
    n = int(round(fill * width))
    bar = "#" * n + "." * (width - n)
    tail = "!" * min(width, int((pressure - 1.0) * width)) \
        if pressure > 1.0 else ""
    return f"[{bar}]{tail} {pressure:.2f}x"


def render_text(p: dict) -> str:
    frac = (p.get("attributed") or {}).get("staged_fraction")
    head = (f"{p['tenants_total']} tenant(s), "
            f"{len(p['over_budget_tenants'])} over budget"
            + (f", attribution {frac:.0%} of process staged bytes"
               if frac is not None else ""))
    lines = [f"wf_tenant: {head}; {p['actionable']} tenant(s) with "
             f"actions"]
    for i, t in enumerate(p["tenants"], 1):
        lines.append(
            f"  #{i} {t['tenant']} "
            f"({', '.join(t['graphs']) or 'no live graphs'}): "
            f"{_bar(t['pressure'])} — {t['hbm_bytes']} B resident"
            + (f" / {t['budget_bytes']} B budget"
               if t["budget_bytes"] else "")
            + (f", heaviest op {t['heaviest_op']}"
               if t.get("heaviest_op") else ""))
        v = t.get("verdict")
        if v:
            tag = "ACTIVE" if t["over_budget"] else "last"
            lines.append(f"      verdict ({tag}): {v.get('message')}")
        for a in t["actions"]:
            if a["kind"] == "throttle_admission":
                lines.append(f"      PLAN throttle_admission x"
                             f"{a['factor']} — {a['note']}")
            elif a["kind"] == "rescale_tenant":
                lines.append(f"      PLAN rescale_tenant shed "
                             f"{a['shed_bytes']} B — {a['note']}")
            elif a["kind"] == "drain_shards":
                lines.append(f"      PLAN drain_shards op="
                             f"{a['op']} — {a['note']}")
            elif a["kind"] == "rebalance_hot_tenant":
                lines.append(f"      PLAN rebalance_hot_tenant — "
                             f"{a['note']}")
        if not t["actions"]:
            lines.append("      (no action)")
    if not p["tenants"]:
        lines.append("  (no tenants registered — is "
                     "Config.tenant_ledger on?)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--stats", metavar="DUMP", required=True,
                    help="stats JSON with a Tenant section (dump_stats "
                         "output, postmortem stats.json, or a bare "
                         "tenant.json)")
    ap.add_argument("--json", action="store_true",
                    help="emit the ranked plan as JSON")
    ap.add_argument("--top", type=int, default=0,
                    help="emit only the worst N tenants")
    ap.add_argument("--check", action="store_true",
                    help="budget gate: exit 1 while any tenant's "
                         "latched OVER_BUDGET verdict is active or "
                         "attribution is under --min-fraction")
    ap.add_argument("--min-fraction", type=float, default=0.9,
                    help="minimum attributed staged fraction --check "
                         "accepts (default 0.9, the CI floor; only "
                         "enforced when the section reports one)")
    args = ap.parse_args(argv)

    ten = load_tenant_section(args.stats)
    adv = _load_advisor()
    p = adv.plan(ten, top=args.top)
    if args.check:
        if p["over_budget_tenants"]:
            worst = p["tenants"][0] if p["tenants"] else {}
            v = worst.get("verdict") or {}
            print(f"wf_tenant: OVER BUDGET — "
                  f"{', '.join(p['over_budget_tenants'])}: "
                  f"{v.get('message', '?')}")
            return 1
        frac = (p.get("attributed") or {}).get("staged_fraction")
        if frac is not None and frac < args.min_fraction:
            print(f"wf_tenant: ATTRIBUTION GAP — only {frac:.1%} of "
                  f"process staged bytes attributed to tenants "
                  f"(floor {args.min_fraction:.0%})")
            return 1
        print(f"wf_tenant: OK — {p['tenants_total']} tenant(s) within "
              f"budget"
              + (f", attribution {frac:.1%}" if frac is not None
                 else ""))
        return 0
    if args.json:
        print(json.dumps(p, indent=2))
    else:
        print(render_text(p))
    return 0 if p["actionable"] else 1


if __name__ == "__main__":
    sys.exit(main())
