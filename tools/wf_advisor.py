#!/usr/bin/env python
"""wf_advisor: rank the fusible operator chains of an application.

CLI face of the fusion advisor (windflow_tpu/analysis/fusion.py),
mirroring ``tools/wf_check.py``: point it at the module that builds your
PipeGraph and get the concrete whole-chain-fusion plan — maximal runs of
adjacent TPU operators one XLA program could replace, ranked by
projected HBM bytes-saved and jitted-dispatches-saved per staged batch.
The plan is what the whole-chain-fusion refactor (ROADMAP item 1)
implements and is judged against.

Usage::

    python tools/wf_advisor.py APP_MODULE          # e.g. myapp.pipeline
    python tools/wf_advisor.py APP_MODULE:ATTR     # a PipeGraph attribute
                                                   # or zero-arg factory
    python tools/wf_advisor.py ... --json          # machine-readable plan
    python tools/wf_advisor.py ... --stats DUMP    # rank by MEASURED
        # per-hop numbers: DUMP is a stats JSON (dump_stats output, a
        # postmortem stats.json, or any dict with a "Sweep" section)
    python tools/wf_advisor.py ... --top N         # best N chains only
    python tools/wf_advisor.py ... --verify DUMP   # projected vs REALIZED:
        # DUMP is a stats JSON from a fusion-ON run; each plan chain is
        # matched against the sweep ledger's fusion section and the
        # projected savings are compared with what the fusion executor
        # (windflow_tpu/fusion) actually delivered

Without ``--stats`` the ranking uses spec-based projections (pre-flight
record specs x batch capacity); with it, the sweep ledger's measured
dispatches-per-batch and boundary bytes.  Exit status: 0 when at least
one fusion candidate was found, 1 when the graph has none, 2 on
usage/load failures.  With ``--verify``: 0 when every fused chain
realized its single dispatch per batch, 1 when a fused chain regressed
(more than one dispatch/batch through the fused hop) or nothing fused
although the plan had executable chains.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: module-level names probed (in order) when no :ATTR is given —
#: identical to tools/wf_check.py so one app module serves both CLIs
FACTORY_NAMES = ("make_graph", "build_graph", "graph", "make_app", "app")


def fail(msg: str) -> None:
    print(f"wf_advisor: FAIL: {msg}", file=sys.stderr)
    sys.exit(2)


def _as_graph(obj):
    from windflow_tpu.graph.pipegraph import PipeGraph
    if isinstance(obj, PipeGraph):
        return obj
    if callable(obj):
        out = obj()
        if isinstance(out, PipeGraph):
            return out
    return None


def load_graph(spec: str):
    """``module`` or ``module:attr`` -> a composed PipeGraph (the
    wf_check loading contract)."""
    mod_name, _, attr = spec.partition(":")
    try:
        mod = importlib.import_module(mod_name)
    except ImportError as e:
        fail(f"cannot import '{mod_name}': {e}")
    if attr:
        if not hasattr(mod, attr):
            fail(f"module '{mod_name}' has no attribute '{attr}'")
        g = _as_graph(getattr(mod, attr))
        if g is None:
            fail(f"'{mod_name}:{attr}' is neither a PipeGraph nor a "
                 "zero-arg factory returning one")
        return g
    from windflow_tpu.graph.pipegraph import PipeGraph
    for name in FACTORY_NAMES:
        if hasattr(mod, name):
            g = _as_graph(getattr(mod, name))
            if g is not None:
                return g
    for name in dir(mod):
        if isinstance(getattr(mod, name), PipeGraph):
            return getattr(mod, name)
    fail(f"no PipeGraph found in '{mod_name}' — expose one (or a factory "
         f"named one of {FACTORY_NAMES}), or pass 'module:attr'")


def load_sweep(path: str):
    """The ``Sweep`` section out of a stats dump / postmortem stats.json
    / bare sweep section file."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        fail(f"cannot read stats dump '{path}': {e}")
    if isinstance(obj, dict) and "per_hop" in obj:
        return obj
    sweep = (obj or {}).get("Sweep")
    if not isinstance(sweep, dict) or not sweep.get("enabled"):
        fail(f"'{path}' carries no enabled 'Sweep' section — run the "
             "graph with Config.sweep_ledger on and dump_stats first")
    return sweep


def render_text(p: dict) -> str:
    lines = [f"wf_advisor: graph '{p['graph']}' — "
             f"{len(p['chains'])} fusion candidate(s)"]
    for i, c in enumerate(p["chains"], 1):
        arrows = " -> ".join(c["ops"])
        status = "chainable today (MultiPipe.chain)" if c["provable_now"] \
            else "needs whole-chain fusion"
        lines.append(f"  #{i} {arrows}")
        lines.append(
            f"      saves {c['dispatches_saved_per_batch']} dispatch(es) "
            f"and ~{c['projected_bytes_saved_per_batch']:.0f} boundary "
            f"bytes per batch ({c['basis']}); {status}")
        if c["donation_miss_bytes_per_batch"]:
            lines.append(
                f"      + {c['donation_miss_bytes_per_batch']:.0f} "
                "bytes/batch of donation-miss copies inside the chain")
        if c["tail_boundary"]:
            lines.append(f"      chain ends here: {c['tail_boundary']}")
    if not p["chains"]:
        lines.append("  (no adjacent TPU hops with compatible "
                     "routing/batch contracts)")
    return "\n".join(lines)


def verify(graph, sweep: dict, as_json: bool) -> int:
    """Projected-vs-realized comparison: each plan chain whose member
    prefix the fusion executor fused (the executor trims unsupported
    tails — fusion/executor.plan_segments) is judged by the fused hop's
    realized dispatches/batch; savings are reported side by side."""
    from windflow_tpu.analysis.fusion import plan
    p = plan(graph)
    fus = sweep.get("fusion") or {}
    realized = {tuple(c["members"]): c for c in fus.get("chains", [])}
    rows = []
    regressed = False
    matched = 0
    for c in p["chains"]:
        ops = tuple(c["ops"])
        hit = None
        for members, rc in realized.items():
            # the executor may fuse a PREFIX of the advisor chain (an
            # unsupported tail dropped) — match the longest prefix
            if members == ops[:len(members)]:
                if hit is None or len(members) > len(hit["members"]):
                    hit = rc
        row = {"plan": list(ops),
               "projected_dispatches_saved":
                   c["dispatches_saved_per_batch"],
               "projected_bytes_saved_per_batch":
                   c["projected_bytes_saved_per_batch"]}
        if hit is None:
            row["realized"] = None
        else:
            matched += 1
            dpb = hit.get("dispatches_per_batch")
            row["realized"] = {
                "fused": hit["name"],
                "dispatches_per_batch": dpb,
                "dispatches_saved_per_batch":
                    hit.get("dispatches_saved_per_batch"),
                "bytes_saved_per_batch": hit.get("bytes_saved_per_batch"),
                "donated_inputs": hit.get("donated_inputs"),
            }
            if dpb is not None and dpb > 1.05:
                # >1 dispatch/batch through the fused hop (small slack
                # for EOS-flush passes amortized over short runs)
                row["regressed"] = True
                regressed = True
        rows.append(row)
    out = {"graph": p["graph"], "chains": rows,
           "realized_total": {
               "dispatches_saved_per_batch":
                   fus.get("dispatches_saved_per_batch"),
               "bytes_saved_per_batch": fus.get("bytes_saved_per_batch")}}
    if as_json:
        print(json.dumps(out, indent=2))
    else:
        print(f"wf_advisor --verify: graph '{p['graph']}' — "
              f"{matched}/{len(rows)} plan chain(s) realized")
        for row in rows:
            arrows = " -> ".join(row["plan"])
            r = row["realized"]
            if r is None:
                print(f"  {arrows}\n      NOT fused (projected "
                      f"{row['projected_dispatches_saved']} dispatch(es) "
                      "saved)")
                continue
            flag = "  REGRESSED" if row.get("regressed") else ""
            print(f"  {arrows}\n      fused as {r['fused']}: "
                  f"{r['dispatches_per_batch']} dispatch/batch "
                  f"(projected saving {row['projected_dispatches_saved']}"
                  f", realized {r['dispatches_saved_per_batch']}); "
                  f"~{r['bytes_saved_per_batch'] or 0:.0f} boundary "
                  f"bytes/batch elided{flag}")
    if regressed:
        return 1
    # "nothing fused" is only a failure when the EXECUTOR itself deems
    # chains executable (fusion/executor.plan_segments trims chains the
    # advisor lists but the executor cannot run — host-interning
    # stateful tails, 1-member runs): an inexecutable plan realizing
    # nothing is correct behavior, not a regression
    from windflow_tpu.fusion.executor import plan_segments
    executable = plan_segments(graph)
    return 1 if (executable and not matched) else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("app", help="APP_MODULE or APP_MODULE:ATTR building "
                                "the PipeGraph")
    ap.add_argument("--json", action="store_true",
                    help="emit the ranked plan as JSON")
    ap.add_argument("--stats", metavar="DUMP",
                    help="stats JSON with a Sweep section: rank by "
                         "measured per-hop numbers")
    ap.add_argument("--verify", metavar="DUMP",
                    help="stats JSON from a fusion-ON run: compare the "
                         "plan's projected savings with the fusion "
                         "executor's realized ones")
    ap.add_argument("--top", type=int, default=0,
                    help="emit only the best N chains")
    args = ap.parse_args(argv)

    g = load_graph(args.app)
    if args.verify:
        return verify(g, load_sweep(args.verify), args.json)
    sweep = load_sweep(args.stats) if args.stats else None
    from windflow_tpu.analysis.fusion import plan
    p = plan(g, sweep=sweep, top=args.top)
    if args.json:
        print(json.dumps(p, indent=2))
    else:
        print(render_text(p))
    return 0 if p["chains"] else 1


if __name__ == "__main__":
    sys.exit(main())
