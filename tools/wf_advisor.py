#!/usr/bin/env python
"""wf_advisor: rank the fusible operator chains of an application.

CLI face of the fusion advisor (windflow_tpu/analysis/fusion.py),
mirroring ``tools/wf_check.py``: point it at the module that builds your
PipeGraph and get the concrete whole-chain-fusion plan — maximal runs of
adjacent TPU operators one XLA program could replace, ranked by
projected HBM bytes-saved and jitted-dispatches-saved per staged batch.
The plan is what the whole-chain-fusion refactor (ROADMAP item 1)
implements and is judged against.

Usage::

    python tools/wf_advisor.py APP_MODULE          # e.g. myapp.pipeline
    python tools/wf_advisor.py APP_MODULE:ATTR     # a PipeGraph attribute
                                                   # or zero-arg factory
    python tools/wf_advisor.py ... --json          # machine-readable plan
    python tools/wf_advisor.py ... --stats DUMP    # rank by MEASURED
        # per-hop numbers: DUMP is a stats JSON (dump_stats output, a
        # postmortem stats.json, or any dict with a "Sweep" section)
    python tools/wf_advisor.py ... --top N         # best N chains only

Without ``--stats`` the ranking uses spec-based projections (pre-flight
record specs x batch capacity); with it, the sweep ledger's measured
dispatches-per-batch and boundary bytes.  Exit status: 0 when at least
one fusion candidate was found, 1 when the graph has none, 2 on
usage/load failures.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: module-level names probed (in order) when no :ATTR is given —
#: identical to tools/wf_check.py so one app module serves both CLIs
FACTORY_NAMES = ("make_graph", "build_graph", "graph", "make_app", "app")


def fail(msg: str) -> None:
    print(f"wf_advisor: FAIL: {msg}", file=sys.stderr)
    sys.exit(2)


def _as_graph(obj):
    from windflow_tpu.graph.pipegraph import PipeGraph
    if isinstance(obj, PipeGraph):
        return obj
    if callable(obj):
        out = obj()
        if isinstance(out, PipeGraph):
            return out
    return None


def load_graph(spec: str):
    """``module`` or ``module:attr`` -> a composed PipeGraph (the
    wf_check loading contract)."""
    mod_name, _, attr = spec.partition(":")
    try:
        mod = importlib.import_module(mod_name)
    except ImportError as e:
        fail(f"cannot import '{mod_name}': {e}")
    if attr:
        if not hasattr(mod, attr):
            fail(f"module '{mod_name}' has no attribute '{attr}'")
        g = _as_graph(getattr(mod, attr))
        if g is None:
            fail(f"'{mod_name}:{attr}' is neither a PipeGraph nor a "
                 "zero-arg factory returning one")
        return g
    from windflow_tpu.graph.pipegraph import PipeGraph
    for name in FACTORY_NAMES:
        if hasattr(mod, name):
            g = _as_graph(getattr(mod, name))
            if g is not None:
                return g
    for name in dir(mod):
        if isinstance(getattr(mod, name), PipeGraph):
            return getattr(mod, name)
    fail(f"no PipeGraph found in '{mod_name}' — expose one (or a factory "
         f"named one of {FACTORY_NAMES}), or pass 'module:attr'")


def load_sweep(path: str):
    """The ``Sweep`` section out of a stats dump / postmortem stats.json
    / bare sweep section file."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        fail(f"cannot read stats dump '{path}': {e}")
    if isinstance(obj, dict) and "per_hop" in obj:
        return obj
    sweep = (obj or {}).get("Sweep")
    if not isinstance(sweep, dict) or not sweep.get("enabled"):
        fail(f"'{path}' carries no enabled 'Sweep' section — run the "
             "graph with Config.sweep_ledger on and dump_stats first")
    return sweep


def render_text(p: dict) -> str:
    lines = [f"wf_advisor: graph '{p['graph']}' — "
             f"{len(p['chains'])} fusion candidate(s)"]
    for i, c in enumerate(p["chains"], 1):
        arrows = " -> ".join(c["ops"])
        status = "chainable today (MultiPipe.chain)" if c["provable_now"] \
            else "needs whole-chain fusion"
        lines.append(f"  #{i} {arrows}")
        lines.append(
            f"      saves {c['dispatches_saved_per_batch']} dispatch(es) "
            f"and ~{c['projected_bytes_saved_per_batch']:.0f} boundary "
            f"bytes per batch ({c['basis']}); {status}")
        if c["donation_miss_bytes_per_batch"]:
            lines.append(
                f"      + {c['donation_miss_bytes_per_batch']:.0f} "
                "bytes/batch of donation-miss copies inside the chain")
        if c["tail_boundary"]:
            lines.append(f"      chain ends here: {c['tail_boundary']}")
    if not p["chains"]:
        lines.append("  (no adjacent TPU hops with compatible "
                     "routing/batch contracts)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("app", help="APP_MODULE or APP_MODULE:ATTR building "
                                "the PipeGraph")
    ap.add_argument("--json", action="store_true",
                    help="emit the ranked plan as JSON")
    ap.add_argument("--stats", metavar="DUMP",
                    help="stats JSON with a Sweep section: rank by "
                         "measured per-hop numbers")
    ap.add_argument("--top", type=int, default=0,
                    help="emit only the best N chains")
    args = ap.parse_args(argv)

    g = load_graph(args.app)
    sweep = load_sweep(args.stats) if args.stats else None
    from windflow_tpu.analysis.fusion import plan
    p = plan(g, sweep=sweep, top=args.top)
    if args.json:
        print(json.dumps(p, indent=2))
    else:
        print(render_text(p))
    return 0 if p["chains"] else 1


if __name__ == "__main__":
    sys.exit(main())
