"""End-to-end decomposition profile for the bench pipeline (VERDICT r4
item 1): break ``PipeGraph.run()`` time into its cost centers so the
kernel↔e2e gap is attacked where it actually is.

Measured pieces (each standalone, on the bench.py e2e pipeline shapes):

  ingest_parse     binary frame bytes -> host columns (native parser)
  staging          host columns -> ONE packed device transfer per batch
  device_map_filter   the chained Map+Filter program on staged batches
  device_ffat      the FFAT window step on staged batches
  egress           fired-window device batches -> host columns (packed D2H)
  e2e_wall         the whole PipeGraph.run() (async overlap included)
  per_op_service   host-side service time per operator from StatsRecords

Because XLA dispatch is asynchronous, the standalone pieces do NOT sum to
the wall time — overlap is the point.  The dominant standalone piece is
the pipeline's floor; ``e2e_wall`` minus the largest piece bounds what
better overlap could recover.

Usage:  python tools/profile_e2e.py [--cpu] [--tuples N] [--json out.json]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--tuples", type=int, default=None)
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import bench as B

    B._setup_compile_cache(jax)   # the bench's own methodology: fresh
    # graph objects re-trace/lower every program; the persistent cache is
    # what keeps the timed run measuring the framework, not the compiler
    dev = jax.devices()[0]
    platform = dev.platform
    cfg = B.CONFIGS[platform]
    CAP, K = cfg["cap"], cfg["keys"]
    n_tuples = args.tuples or cfg["e2e_tuples"]
    n_batches = max(1, n_tuples // CAP)

    rng = np.random.default_rng(1)
    rec = np.empty(n_tuples, dtype=[("k", "<i8"), ("t", "<i8"),
                                    ("v", "<f8")])
    rec["k"] = rng.integers(0, K, n_tuples)
    rec["t"] = np.arange(n_tuples)
    rec["v"] = rng.random(n_tuples)
    blob = rec.tobytes()

    def med(fn, reps=3):
        ts = sorted(fn() for _ in range(reps))
        return ts[len(ts) // 2]

    result = {"platform": platform, "device": str(dev),
              "config": {"cap": CAP, "keys": K, "tuples": n_tuples,
                         "batches": n_batches}}

    # -- 1. ingest parse (bytes -> host columns, native path) --------------
    from windflow_tpu import native

    def parse_once():
        t0 = time.perf_counter()
        for b in range(n_batches):
            lo = b * CAP * 24
            native.parse_frames(blob[lo:lo + CAP * 24], 1)
        return time.perf_counter() - t0

    keys_np, ts_np, vals_np, _ = native.parse_frames(blob[:CAP * 24], 1)
    result["ingest_parse_s"] = round(med(parse_once), 4)

    # -- 2. staging (host columns -> one packed transfer per batch) --------
    import jax.numpy as jnp

    from windflow_tpu.batch import columns_to_device

    payload_cols = {"key": keys_np.astype(np.int32),
                    "v0": vals_np[:, 0].astype(np.float32)}

    def stage_once():
        t0 = time.perf_counter()
        outs = [columns_to_device(payload_cols, ts_np, CAP)
                for _ in range(n_batches)]
        jax.block_until_ready([o.payload for o in outs])
        return time.perf_counter() - t0

    db0 = columns_to_device(payload_cols, ts_np, CAP)
    jax.block_until_ready(db0.payload)
    result["staging_s"] = round(med(stage_once), 4)
    result["staging_mb_per_batch"] = round(CAP * 16 / 1e6, 2)

    # -- 3. device programs (pre-staged, the kernel methodology) -----------
    map_fn = lambda t: {"key": t["key"], "v0": t["v0"] * 1.5 + 1.0}
    filt = lambda t: (t["key"] & 7) != 7

    @jax.jit
    def mf(payload, valid):
        p2 = jax.vmap(map_fn)(payload)
        return p2, valid & jax.vmap(filt)(p2)

    import math

    from windflow_tpu.windows.ffat_kernels import (make_ffat_state,
                                                   make_ffat_step)
    Pn = math.gcd(cfg["win"], cfg["slide"])
    R, D = cfg["win"] // Pn, cfg["slide"] // Pn
    step = jax.jit(make_ffat_step(CAP, K, Pn, R, D, lambda x: x["v0"],
                                  lambda a, b: a + b, lambda x: x["key"]),
                   donate_argnums=(0,))
    state = jax.device_put(
        make_ffat_state(jnp.zeros((), jnp.float32), K, R), dev)

    p2, keep = mf(db0.payload, db0.valid)
    st, out, fired, _ = step(state, p2, db0.ts, keep)
    jax.block_until_ready(st)

    def dev_mf_once():
        t0 = time.perf_counter()
        for _ in range(n_batches):
            p, kp = mf(db0.payload, db0.valid)
        jax.block_until_ready(kp)
        return time.perf_counter() - t0

    result["device_map_filter_s"] = round(med(dev_mf_once), 4)

    def dev_ffat_once():
        nonlocal st
        t0 = time.perf_counter()
        for _ in range(n_batches):
            st, o, f, _ = step(st, p2, db0.ts, keep)
        jax.block_until_ready(st)
        return time.perf_counter() - t0

    result["device_ffat_s"] = round(med(dev_ffat_once), 4)

    # -- 4. egress (fired windows -> host columns, packed D2H) -------------
    from windflow_tpu.batch import DeviceBatch, device_to_columns_multi

    out_db = DeviceBatch(out, jnp.zeros(fired.shape[0], jnp.int64), fired,
                         watermark=0, size=None)

    def egress_once():
        t0 = time.perf_counter()
        for _ in range(n_batches):
            device_to_columns_multi([out_db])
        return time.perf_counter() - t0

    result["egress_s"] = round(med(egress_once), 4)

    # -- 5. whole PipeGraph.run() with per-op service times ----------------
    def chunks():
        for lo in range(0, len(blob), 1 << 20):
            yield blob[lo:lo + (1 << 20)]

    g = B._e2e_graph(cfg, n_tuples, chunks, lambda c: None)
    g.run()                                     # warm: compile everything

    g2 = B._e2e_graph(cfg, n_tuples, chunks, lambda c: None)
    t0 = time.perf_counter()
    g2.run()
    wall = time.perf_counter() - t0
    result["e2e_wall_s"] = round(wall, 4)
    result["e2e_tuples_per_sec"] = round(n_tuples / wall, 1)

    per_op = {}
    for op in g2._operators:
        per_op[op.name] = round(sum(
            r.stats.service_time_usec for r in op.replicas) / 1e6, 4)
    result["per_op_service_s"] = per_op
    result["service_total_s"] = round(sum(per_op.values()), 4)
    result["driver_residual_s"] = round(
        wall - sum(per_op.values()), 4)

    pieces = {k: result[k] for k in ("ingest_parse_s", "staging_s",
                                     "device_map_filter_s", "device_ffat_s",
                                     "egress_s")}
    result["dominant_piece"] = max(pieces, key=pieces.get)

    line = json.dumps(result, indent=2)
    print(line)
    if args.json:
        with open(args.json, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
