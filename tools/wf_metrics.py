#!/usr/bin/env python
"""wf_metrics: standalone OpenMetrics exporter for windflow_tpu stats.

Renders a ``PipeGraph.stats()`` JSON dump (what ``dump_stats()`` writes,
or any ``/apps/<id>/latest`` dashboard payload) in Prometheus text
exposition format — the offline counterpart of the dashboard's live
``GET /metrics`` endpoint.  Loads ``monitoring/openmetrics.py``
file-direct (pure stdlib), so it runs on scrape/relay hosts with no jax
installed.

Usage::

    python tools/wf_metrics.py log/app_stats.json            # render
    python tools/wf_metrics.py log/app_stats.json --check    # render,
        # then re-parse with the strict exposition parser: exit 1 on any
        # format violation (escaping, bucket monotonicity, typing)
    python tools/wf_metrics.py --check http://localhost:20208/metrics
        # validate a live dashboard endpoint instead of a file
    python tools/wf_metrics.py log/app_stats.json --serve 9100
        # tiny exporter: GET /metrics re-reads + re-renders the file per
        # scrape (point a Prometheus job at it)

The CI golden-format tests (tests/test_device_metrics.py) run the same
``--check`` round trip over a real graph's stats dump.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_openmetrics():
    """File-direct import of monitoring/openmetrics.py: skips the
    ``windflow_tpu`` package __init__ (which imports jax)."""
    path = os.path.join(REPO, "windflow_tpu", "monitoring",
                        "openmetrics.py")
    spec = importlib.util.spec_from_file_location("_wf_openmetrics", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _read_source(src: str) -> tuple:
    """(kind, payload): exposition text from an http(s) URL, stats JSON
    from a file path or '-' (stdin)."""
    if src.startswith(("http://", "https://")):
        import urllib.request
        with urllib.request.urlopen(src, timeout=10) as r:
            return "exposition", r.read().decode("utf-8", "replace")
    text = sys.stdin.read() if src == "-" else open(src).read()
    return "stats", json.loads(text)


def render(stats: dict, om) -> str:
    return om.render_openmetrics(stats)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("source", help="stats JSON path, '-' for stdin, or an "
                                   "http(s) /metrics URL (with --check)")
    ap.add_argument("--check", action="store_true",
                    help="validate the exposition with the strict parser "
                         "instead of printing it")
    ap.add_argument("--serve", type=int, metavar="PORT",
                    help="serve GET /metrics, re-reading the stats file "
                         "on every scrape")
    args = ap.parse_args(argv)
    om = _load_openmetrics()

    kind, payload = _read_source(args.source)
    if kind == "exposition":
        if not args.check:
            print("wf_metrics: URL sources are for --check (the endpoint "
                  "already serves exposition)", file=sys.stderr)
            return 2
        text = payload
    else:
        text = render(payload, om)

    if args.check:
        try:
            families = om.parse_exposition(text)
        except ValueError as e:
            print(f"wf_metrics: FAIL: {e}", file=sys.stderr)
            return 1
        n = sum(len(f["samples"]) for f in families.values())
        print(f"wf_metrics: OK ({len(families)} families, {n} samples)")
        return 0

    if args.serve is not None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        src = args.source

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                try:
                    _, stats = _read_source(src)
                    body = render(stats, om).encode()
                    code = 200
                except (OSError, ValueError) as e:
                    body = f"# wf_metrics error: {e}\n".encode()
                    code = 500
                self.send_response(code)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        server = ThreadingHTTPServer(("0.0.0.0", args.serve), Handler)
        print(f"wf_metrics: serving {src} at "
              f"http://0.0.0.0:{server.server_address[1]}/metrics")
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            pass
        return 0

    sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
