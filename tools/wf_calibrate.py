#!/usr/bin/env python
"""wf_calibrate: probe the live backend, write calibration.json.

The shard ledger's ICI model, the tenant ledger's modeled ICI share,
the roofline ceiling, and ``bench.py``'s gap diagnosis all compute
from constants (``calibration.MODELED_DEFAULTS``) that were, until
this tool, hardcoded guesses.  ``wf_calibrate`` measures them — a
short seeded probe suite on the backend this process actually has —
and writes a versioned ``calibration.json`` keyed by device kind +
jax version.  Point ``Config.calibration`` / ``WF_TPU_CALIBRATION``
at the file and every read site flips from ``modeled`` to
``calibrated(<age>)`` provenance until the store goes stale
(``WF_TPU_CALIBRATION_TTL_S``, default 7 days) or the device kind
changes (docs/OBSERVABILITY.md "Calibration plane").

Probes (all seeded, a few seconds total):

* ``h2d_tunnel_bytes_per_sec`` — median host→device transfer rate of
  a packed staging buffer (the SAME ``PackedBatchBuilder`` path the
  runtime stages batches through, so the number is the tunnel the
  staged e2e leg actually pays).
* ``dispatch_overhead_usec`` — wall cost of dispatching one cached
  trivial jitted program (the per-dispatch floor the megastep fold
  amortizes).
* ``sampled_sync_usec`` — one ``block_until_ready`` device sync (what
  each ``trace_device_sync_every``-sampled batch pays).
* ``hbm_bytes_per_sec`` — effective memory bandwidth of a large
  compiled elementwise copy (the roofline ceiling; on the CPU
  fallback this measures host memory, honestly).
* ``kernel_step_usec`` — one fused FFAT window step at the bench
  shape (the per-device-kind step timing the roofline cross-checks).
* ``ici_bytes_per_sec`` — psum ring bandwidth across the mesh; only
  recorded on a multi-device backend (``MESH_ONLY_KEYS``).

Usage::

    python tools/wf_calibrate.py                  # probe + write
    python tools/wf_calibrate.py --out cal.json   # elsewhere
    python tools/wf_calibrate.py --check [PATH]   # validate only:
        # exit 0 fresh+valid, 1 stale/corrupt/missing, 2 kill switch

``--check`` is pure stdlib (no jax import — loads calibration.py
file-direct, the wf_metrics pattern) so CI relay hosts can gate on it.
The refuse-to-report-clean stance: a missing or stale store exits 1,
and the ``WF_TPU_CALIBRATION=0`` kill switch exits 2 — a pipeline
that *meant* to be calibrated must hear that it is not.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_OUT = os.path.join(REPO, "calibration.json")
if REPO not in sys.path:        # script runs live with tools/ as
    sys.path.insert(0, REPO)    # sys.path[0]; the probes need the package


def _load_calibration_mod():
    """File-direct import of monitoring/calibration.py: skips the
    ``windflow_tpu`` package __init__ (which imports jax), so --check
    runs on hosts with no jax at all."""
    path = os.path.join(REPO, "windflow_tpu", "monitoring",
                        "calibration.py")
    spec = importlib.util.spec_from_file_location("_wf_calibration", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


# ---------------------------------------------------------------------------
# probes (each returns (value, probe_detail))
# ---------------------------------------------------------------------------

def probe_h2d(jax, np, reps: int = 7):
    """Host→device staging rate over the runtime's own packed path."""
    from windflow_tpu.staging import PackedBatchBuilder
    cap = 1 << 18                         # 256k rows ≈ 3 MB packed
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 20, cap).astype(np.int32)
    vals = rng.random(cap, dtype=np.float32)
    tss = np.arange(cap, dtype=np.int64)
    dev = jax.devices()[0]
    rates = []
    buf_bytes = None
    for _ in range(reps):
        b = PackedBatchBuilder([np.int32, np.float32], cap)
        b.append([keys, vals], tss)
        host = b.finish()
        buf_bytes = host.nbytes
        t0 = time.perf_counter()
        d = jax.device_put(host, dev)
        jax.block_until_ready(d)
        rates.append(host.nbytes / (time.perf_counter() - t0))
        b.pool.release(host, d)
    return _median(rates), {"buffer_bytes": buf_bytes, "reps": reps}


def probe_dispatch(jax, np, reps: int = 200):
    """Per-dispatch overhead of a cached trivial program (µs)."""
    import jax.numpy as jnp
    x = jax.device_put(jnp.zeros(8, jnp.float32))
    f = jax.jit(lambda a: a + 1.0)
    jax.block_until_ready(f(x))          # compile outside the clock
    t0 = time.perf_counter()
    y = x
    for _ in range(reps):
        y = f(y)
    jax.block_until_ready(y)
    usec = (time.perf_counter() - t0) * 1e6 / reps
    return usec, {"reps": reps}


def probe_sync(jax, np, reps: int = 50):
    """One sampled block_until_ready round trip (µs)."""
    import jax.numpy as jnp
    x = jax.device_put(jnp.zeros(8, jnp.float32))
    f = jax.jit(lambda a: a + 1.0)
    jax.block_until_ready(f(x))
    ts = []
    for _ in range(reps):
        y = f(x)
        t0 = time.perf_counter()
        jax.block_until_ready(y)
        ts.append((time.perf_counter() - t0) * 1e6)
    return _median(ts), {"reps": reps}


def probe_hbm(jax, np, reps: int = 7):
    """Effective memory bandwidth of a compiled elementwise copy: the
    program reads + writes the array once, so bytes = 2 * nbytes."""
    import jax.numpy as jnp
    n = 1 << 24                           # 64 MB f32
    x = jax.device_put(jnp.ones(n, jnp.float32))
    f = jax.jit(lambda a: a * 1.0000001)
    jax.block_until_ready(f(x))
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f(x))
        rates.append(2 * x.nbytes / (time.perf_counter() - t0))
    return _median(rates), {"array_bytes": int(x.nbytes), "reps": reps}


def probe_kernel_step(jax, np, reps: int = 5):
    """One fused FFAT window step at the bench shape (µs/step)."""
    import jax.numpy as jnp
    from windflow_tpu.windows.ffat_kernels import (make_ffat_state,
                                                   make_ffat_step)
    cap, keys, win, slide = 8192, 256, 16, 4
    import math as _math
    pn = _math.gcd(win, slide)
    step = jax.jit(make_ffat_step(
        cap, keys, pn, win // pn, slide // pn,
        lambda x: x["v"], lambda a, b: a + b, lambda x: x["k"],
        monoid="sum"))
    rng = np.random.default_rng(1)
    payload = {
        "k": jnp.asarray(rng.integers(0, keys, cap), jnp.int32),
        "v": jnp.asarray(rng.random(cap), jnp.float32),
    }
    tss = jnp.arange(cap, dtype=jnp.int64)
    valid = jnp.ones(cap, bool)
    st = make_ffat_state(jnp.zeros((), jnp.float32), keys, win // pn)
    st, out, fired, _ = step(st, payload, tss, valid)
    jax.block_until_ready(st)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        s = st
        for _ in range(10):
            s, out, fired, _ = step(s, payload, tss, valid)
        jax.block_until_ready(s)
        ts.append((time.perf_counter() - t0) * 1e6 / 10)
    return _median(ts), {"cap": cap, "keys": keys, "reps": reps}


def probe_ici(jax, np, reps: int = 7):
    """psum ring bandwidth across the mesh — multi-device only."""
    import jax.numpy as jnp
    ndev = jax.device_count()
    if ndev < 2:
        return None, {"note": f"single device ({ndev}) — skipped"}
    n = 1 << 20                           # 4 MB f32 per device
    x = jnp.ones((ndev, n), jnp.float32)
    f = jax.pmap(lambda a: jax.lax.psum(a, "i"), axis_name="i")
    jax.block_until_ready(f(x))
    rates = []
    # ring all-reduce moves ~2*(N-1)/N of the payload per device
    moved = 2 * (ndev - 1) / ndev * n * 4 * ndev
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f(x))
        rates.append(moved / (time.perf_counter() - t0))
    return _median(rates), {"devices": ndev, "payload_bytes": n * 4,
                            "reps": reps}


PROBES = (
    ("h2d_tunnel_bytes_per_sec", probe_h2d),
    ("dispatch_overhead_usec", probe_dispatch),
    ("sampled_sync_usec", probe_sync),
    ("hbm_bytes_per_sec", probe_hbm),
    ("kernel_step_usec", probe_kernel_step),
    ("ici_bytes_per_sec", probe_ici),
)


def calibrate(out_path: str) -> int:
    calib = _load_calibration_mod()
    if calib.killed():
        print("wf_calibrate: FAIL: WF_TPU_CALIBRATION=0 — the kill "
              "switch is on; unset it to calibrate", file=sys.stderr)
        return 2
    import jax
    import numpy as np
    dev = jax.devices()[0]
    kind = str(getattr(dev, "device_kind", None) or dev.platform)
    constants, probes = {}, {}
    for key, fn in PROBES:
        try:
            value, detail = fn(jax, np)
        except Exception as e:  # lint: broad-except-ok (one dead probe
            # must not lose the others' measurements; the key simply
            # stays modeled and the detail names why)
            probes[key] = {"error": f"{type(e).__name__}: {e}"[:200]}
            print(f"wf_calibrate: note: probe {key} failed "
                  f"({type(e).__name__}: {e})")
            continue
        probes[key] = detail
        if value is not None:
            constants[key] = round(float(value), 3)
            print(f"wf_calibrate: {key} = {constants[key]}")
        else:
            print(f"wf_calibrate: {key} skipped "
                  f"({detail.get('note', 'no value')})")
    if not constants:
        print("wf_calibrate: FAIL: every probe failed — nothing to "
              "write", file=sys.stderr)
        return 1
    store = calib.CalibrationStore({
        "schema": calib.SCHEMA,
        "recorded_at": time.time(),
        "device_kind": kind,
        "backend": dev.platform,
        "jax_version": jax.__version__,
        "constants": constants,
        "probes": probes,
    }, path=out_path)
    with open(out_path, "w") as f:
        json.dump(store.to_json(), f, indent=2)
        f.write("\n")
    print(f"wf_calibrate: wrote {out_path} ({len(constants)} constant(s) "
          f"for {kind}, jax {jax.__version__})")
    return 0


def check(path: str) -> int:
    """Validate-only (stdlib, no jax): the CI gate."""
    calib = _load_calibration_mod()
    if calib.killed():
        # the kill switch means "deliberately uncalibrated" — distinct
        # exit code so a pipeline that MEANT to calibrate can tell the
        # difference from a stale store
        print("wf_calibrate: kill switch (WF_TPU_CALIBRATION=0) — "
              "calibration disabled process-wide", file=sys.stderr)
        return 2
    try:
        store = calib.load(path)
    except calib.CalibrationError as e:
        print(f"wf_calibrate: FAIL: {path}: {e}", file=sys.stderr)
        return 1
    age = store.age_s()
    if not store.fresh():
        print(f"wf_calibrate: FAIL: {path} is {age / 86400:.1f} days old "
              f"(TTL {calib.TTL_S / 86400:.1f}d) — constants would "
              "degrade to modeled; re-run wf_calibrate", file=sys.stderr)
        return 1
    missing = [k for k in calib.MODELED_DEFAULTS
               if k not in store.constants
               and k not in calib.MESH_ONLY_KEYS]
    note = f", {len(missing)} key(s) still modeled: {missing}" \
        if missing else ""
    print(f"wf_calibrate: OK ({path}: {len(store.constants)} constant(s) "
          f"for {store.device_kind}, jax {store.jax_version}, age "
          f"{age / 3600:.1f}h{note})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=DEFAULT_OUT,
                    help=f"output path (default {DEFAULT_OUT})")
    ap.add_argument("--check", nargs="?", const="", metavar="PATH",
                    help="validate an existing store instead of probing "
                         "(default: --out, then WF_TPU_CALIBRATION)")
    args = ap.parse_args(argv)
    if args.check is not None:
        path = args.check or os.environ.get("WF_TPU_CALIBRATION") \
            or args.out
        return check(path)
    return calibrate(args.out)


if __name__ == "__main__":
    sys.exit(main())
