#!/usr/bin/env python
"""Export flight-recorder span events as Chrome-trace JSON.

``PipeGraph.dump_trace()`` writes two files under ``Config.log_dir``: the
Chrome trace itself (``{app}_trace.json``) and the raw span events
(``{app}_events.json``).  This tool re-renders the raw events offline —
useful when a long run dumped only the (small) raw events, or when
re-exporting after a recorder format change — and validates that a trace
file is loadable Chrome-trace JSON.

Usage::

    python tools/trace_export.py APP_events.json            # -> APP_trace.json
    python tools/trace_export.py APP_events.json -o OUT.json
    python tools/trace_export.py --check APP_trace.json     # schema check

Open the result in Perfetto (https://ui.perfetto.dev) or
``chrome://tracing``; timestamps are wall-clock microseconds, the same
domain as a ``jax.profiler`` capture taken during the run, so the two load
side by side (docs/OBSERVABILITY.md).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from windflow_tpu.monitoring.recorder import (STAGE_NAMES,  # noqa: E402
                                              chrome_trace_from_events)

_EVENT_KEYS = {"op", "replica", "trace", "stage", "t_usec"}
_PHASES = {"M", "i", "b", "e", "X"}


def fail(msg: str) -> None:
    print(f"trace_export: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def check_chrome_trace(obj) -> int:
    """Validate the subset of the Chrome-trace schema the recorder emits
    (and that Perfetto requires): a ``traceEvents`` array whose entries
    carry name/ph/pid, a numeric ``ts`` on every timed phase, and only
    known phase codes.  Returns the event count."""
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        fail("not a Chrome trace: no 'traceEvents' key")
    evs = obj["traceEvents"]
    if not isinstance(evs, list) or not evs:
        fail("'traceEvents' empty or not a list")
    for i, e in enumerate(evs):
        for k in ("name", "ph", "pid"):
            if k not in e:
                fail(f"traceEvents[{i}] missing '{k}': {e}")
        if e["ph"] not in _PHASES:
            fail(f"traceEvents[{i}] unknown phase {e['ph']!r}")
        if e["ph"] != "M" and not isinstance(e.get("ts"), (int, float)):
            fail(f"traceEvents[{i}] ({e['ph']}) has no numeric 'ts'")
    return len(evs)


def load_events(path: str) -> list:
    with open(path) as f:
        events = json.load(f)
    if not isinstance(events, list):
        fail(f"{path}: expected a JSON array of span events")
    for i, e in enumerate(events):
        if not isinstance(e, dict) or not _EVENT_KEYS <= set(e):
            fail(f"{path}[{i}]: not a span event (need {sorted(_EVENT_KEYS)})")
        if e["stage"] not in STAGE_NAMES:
            fail(f"{path}[{i}]: unknown stage {e['stage']!r}")
    return events


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("input", help="raw events JSON (or a Chrome trace "
                                  "with --check)")
    ap.add_argument("-o", "--output", default=None,
                    help="output path (default: derive <app>_trace.json)")
    ap.add_argument("--check", action="store_true",
                    help="validate an existing Chrome-trace file instead "
                         "of exporting")
    args = ap.parse_args()

    if args.check:
        with open(args.input) as f:
            n = check_chrome_trace(json.load(f))
        print(f"trace_export: OK ({args.input}: {n} events)")
        return

    events = load_events(args.input)
    out = args.output
    if out is None:
        root, ext = os.path.splitext(args.input)
        base = root[:-len("_events")] if root.endswith("_events") else root
        out = f"{base}_trace{ext or '.json'}"
    trace = chrome_trace_from_events(events)
    check_chrome_trace(trace)
    with open(out, "w") as f:
        json.dump(trace, f)
    print(f"trace_export: OK ({len(events)} span events -> {out}, "
          f"{len(trace['traceEvents'])} trace events)")


if __name__ == "__main__":
    main()
