#!/usr/bin/env python
"""wf_shard: rank shard imbalance and emit a rebalance plan.

CLI face of the reshard advisor (windflow_tpu/analysis/resharding.py),
mirroring ``tools/wf_advisor.py``: point it at a stats dump carrying a
``Shard`` section (a ``dump_stats`` JSON, a postmortem ``stats.json`` /
``shard.json``, or a bare section file) and get every keyed operator
ranked by per-shard load imbalance, the hot-key table, and the concrete
key→shard rebalance contract a resharding executor implements
(``plan(...)`` — the interface an elastic/resharding executor PR
implements, exactly as ``wf_advisor.plan`` was the whole-chain-fusion
executor's contract).

Usage::

    python tools/wf_shard.py --stats DUMP            # rank + plan
    python tools/wf_shard.py APP_MODULE --stats DUMP # graph named from
                                                     # the app module
    python tools/wf_shard.py ... --json              # machine-readable
    python tools/wf_shard.py ... --threshold 1.5     # imbalance bound
    python tools/wf_shard.py ... --top N             # worst N ops only

This tool never imports jax (``wf_metrics``/``wf_doctor`` scrape-host
stance) unless an APP_MODULE is given to name the graph.  Exit status:
0 when at least one operator has rebalance actions, 1 when every keyed
operator is balanced (nothing to do), 2 on usage/load failures.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_resharding():
    """File-direct import of analysis/resharding.py (pure stdlib):
    skips the ``windflow_tpu`` package __init__, which imports jax —
    the ``wf_metrics``/``wf_doctor`` scrape-host stance."""
    path = os.path.join(REPO, "windflow_tpu", "analysis", "resharding.py")
    spec = importlib.util.spec_from_file_location("_wf_resharding", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def fail(msg: str) -> None:
    print(f"wf_shard: FAIL: {msg}", file=sys.stderr)
    sys.exit(2)


def load_shard_section(path: str) -> dict:
    """The ``Shard`` section out of a stats dump / postmortem
    stats.json / bare shard.json file."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        fail(f"cannot read stats dump '{path}': {e}")
    if isinstance(obj, dict) and "per_op" in obj:
        return obj
    shard = (obj or {}).get("Shard")
    if not isinstance(shard, dict) or not shard.get("enabled"):
        fail(f"'{path}' carries no enabled 'Shard' section — run the "
             "graph with Config.shard_ledger on and dump_stats first")
    return shard


def render_text(p: dict) -> str:
    lines = [f"wf_shard: graph '{p.get('graph') or '?'}' — "
             f"{p['actionable']} operator(s) above imbalance threshold "
             f"{p['threshold']}"]
    for i, o in enumerate(p["ops"], 1):
        lines.append(
            f"  #{i} {o['op']} ({o['n_shards']} shard(s), "
            f"{o['placement']}, basis {o['basis']}): "
            f"imbalance {o['imbalance_ratio']}, "
            f"hot shard {o['hot_shard']}, loads {o['loads']}")
        if o.get("hot_keys"):
            hk = o["hot_keys"][0]
            lines.append(
                f"      hottest key {hk.get('key')} ~{hk.get('est_tuples')}"
                f" tuple(s) ({100 * (hk.get('share') or 0):.1f}% of the "
                f"stream) on shard {hk.get('shard')}")
        if o.get("lag_spread_usec") is not None:
            lines.append(f"      watermark-lag spread across shards: "
                         f"{o['lag_spread_usec'] / 1e3:.1f} ms")
        for a in o["actions"]:
            if a["kind"] == "move_keys":
                mv = ", ".join(
                    f"{m['key']}: {m['from_shard']}→{m['to_shard']} "
                    f"(~{m['est_tuples']})" for m in a["moves"])
                lines.append(
                    f"      PLAN move_keys [{mv}] — projected imbalance "
                    f"{a['projected_imbalance_ratio']}")
            elif a["kind"] == "split_hot_key":
                lines.append(
                    f"      PLAN split_hot_key {a['key']} "
                    f"(~{a['est_tuples']} tuple(s)): {a['note']}")
        if not o["actions"]:
            lines.append("      balanced (no action)")
    if not p["ops"]:
        lines.append("  (no keyed operator with a measured load — is "
                     "the shard ledger on and the graph keyed?)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("app", nargs="?",
                    help="optional APP_MODULE[:ATTR] building the "
                         "PipeGraph (names the graph in the plan; the "
                         "wf_advisor loading contract)")
    ap.add_argument("--stats", metavar="DUMP", required=True,
                    help="stats JSON with a Shard section (dump_stats "
                         "output, postmortem stats.json, or a bare "
                         "shard section / shard.json)")
    ap.add_argument("--json", action="store_true",
                    help="emit the ranked plan as JSON")
    ap.add_argument("--threshold", type=float, default=None,
                    help="max/mean load ratio above which an operator "
                         "gets rebalance actions (default 1.25)")
    ap.add_argument("--top", type=int, default=0,
                    help="emit only the worst N operators")
    args = ap.parse_args(argv)

    graph_name = None
    if args.app:
        # reuse wf_advisor's loader so one app module serves both CLIs
        # (this path DOES import the package, jax included)
        from tools.wf_advisor import load_graph
        graph_name = load_graph(args.app).name
    shard = load_shard_section(args.stats)
    rs = _load_resharding()
    p = rs.plan(shard, graph_name=graph_name,
                threshold=args.threshold if args.threshold is not None
                else rs.DEFAULT_THRESHOLD,
                top=args.top)
    if args.json:
        print(json.dumps(p, indent=2))
    else:
        print(render_text(p))
    return 0 if p["actionable"] else 1


if __name__ == "__main__":
    sys.exit(main())
