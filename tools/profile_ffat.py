"""FFAT kernel component micro-profile (VERDICT r4 item 2).

Breaks the FFAT CB step (windows/ffat_kernels.make_ffat_step, bench
shapes) into its pipeline stages and times each as a standalone jitted
program, so the dominant component is MEASURED before any kernel work:

  key_extract_argsort   stable argsort of the key lane (the sort pass)
  grouping_rank_scatter the O(n) counting permutation (windows/grouping.py)
  sort_gather           argsort + payload/lift gather (sort + data motion)
  rank_scan             segment-start max-scan -> per-lane rank (pre-r5)
  rank_hist             histogram + [K+1] cumsum -> per-lane rank (live)
  pane_cells            segmented scan + scatter into [K+1, NP] pane cells
  sliding_fold          flag-aware dilated log2(R) fold over pane rows
  sliding_fold_plain    flagless fold (withSumCombiner variant)
  sliding_fold_cumsum   cumsum-diff alternative (sum-only; for comparison)
  firing_compact        per-key prefix counts + searchsorted compaction
  full_step             the complete fused step (reference point)

Each timing is the median of 5 windows of `--steps` dispatches on
pre-staged device batches (the bench.py methodology).  Components overlap
inside the fused step (XLA may fuse/elide across them), so shares are
indicative, not additive — the point is the ORDER and the dominant term.

**In-fused-step ablation** (``ablation_ms``; the PROFILE_r05 honesty
fix): standalone component times over-count what a region costs INSIDE
the fused step, where XLA overlaps and fuses across regions (the r05
components each "cost" ~100-120% of the whole step).  The ablation mode
instead swaps ONE region for an identity stub (same shapes, no work) via
the module seams the step builder calls through, re-times the WHOLE
step, and attributes ``full_ms - ablated_ms`` to the region — a real
fused-step delta, the number a Pallas win must be judged against.
Regions: ``grouping`` (order+hist), ``pane_scan`` (segmented scan),
``sliding_fold`` (window fold).

**Pallas comparison** (``pallas_compare``; docs/PERF.md round 14): the
full fused step timed with the Pallas kernels selected
(windflow_tpu/kernels, Config.pallas_kernels resolution) against the
pure-lax build of the SAME step, for both the generic-combiner path and
the declared-monoid path — the bench ``pallas`` section's
methodology, at profile shapes.  On CPU the kernels run under the
Pallas interpreter (``interpret_mode: true``): a correctness vehicle,
expected SLOWER than lax — real speedups are TPU numbers.

Usage:  python tools/profile_ffat.py [--cpu] [--json out.json]
"""

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def build_components(jax, jnp, CAP, K, Pn, R):
    """Return {name: (jitted_fn, args_builder)} component programs mirroring
    the stages of windows/ffat_kernels.make_ffat_step (cited per stage)."""
    from windflow_tpu.windows.ffat_kernels import (_seg_scan,
                                                   _sliding_reduce,
                                                   _sliding_reduce_plain)

    NP1 = CAP // Pn + 2
    comb = lambda a, b: a + b

    def key_extract_argsort(payload, valid):
        keys = payload["k"]
        sk = jnp.where(valid & (keys >= 0) & (keys < K), keys, K)
        return jnp.argsort(sk, stable=True)

    def grouping_rank_scatter(payload, valid):
        from windflow_tpu.windows.grouping import counting_order
        keys = payload["k"]
        sk = jnp.where(valid & (keys >= 0) & (keys < K), keys, K)
        return counting_order(sk, K + 1)

    def sort_gather(payload, valid):
        keys = payload["k"]
        sk = jnp.where(valid & (keys >= 0) & (keys < K), keys, K)
        order = jnp.argsort(sk, stable=True)
        return sk[order], payload["v"][order]

    def rank_scan(sk_sorted):
        # the pre-r5 rank stage (kept for comparison): [CAP]-length
        # associative max-scan over segment starts
        pos = jnp.arange(CAP)
        starts = jnp.concatenate(
            [jnp.array([True]), sk_sorted[1:] != sk_sorted[:-1]])
        seg_start = jax.lax.associative_scan(
            jnp.maximum, jnp.where(starts, pos, 0))
        return pos - seg_start

    def rank_hist(payload, valid, sk_sorted):
        # the live rank stage (ffat_kernels.py step, permutation branch):
        # histogram of the UNSORTED keys + [K+1] exclusive cumsum —
        # rank = pos - bucket_start[sorted key], no [CAP]-length scan
        keys = payload["k"]
        sk = jnp.where(valid & (keys >= 0) & (keys < K), keys, K)
        hist = jnp.zeros(K + 1, jnp.int32).at[sk].add(1)
        bucket_start = jnp.cumsum(hist) - hist
        return jnp.arange(CAP) - bucket_start[sk_sorted]

    def pane_cells(sk_sorted, v_sorted, pane_rel):
        starts = jnp.concatenate(
            [jnp.array([True]), sk_sorted[1:] != sk_sorted[:-1]])
        pane_starts = starts | jnp.concatenate(
            [jnp.array([True]), pane_rel[1:] != pane_rel[:-1]])
        scanned = _seg_scan(comb, pane_starts, v_sorted)
        ends = jnp.concatenate(
            [(sk_sorted[1:] != sk_sorted[:-1])
             | (pane_rel[1:] != pane_rel[:-1]), jnp.array([True])])
        row = jnp.where(ends, sk_sorted, K)
        col = jnp.where(ends, pane_rel, 0)
        buf = jnp.zeros((K + 1, NP1), scanned.dtype)
        return buf.at[row, col].set(jnp.where(ends, scanned, 0))[:K]

    def sliding_fold(cells, cell_has):
        _, v = _sliding_reduce(comb, cell_has, cells, R, axis=1)
        return v

    def sliding_fold_plain(cells, cell_has):
        return _sliding_reduce_plain(comb, cell_has, cells, R, axis=1,
                                     monoid="sum")

    def sliding_fold_cumsum(cells, cell_has):
        # cumsum-diff: out[i] = cs[i] - cs[i-R]; sum-only alternative
        z = jnp.where(cell_has, cells, 0)
        cs = jnp.cumsum(z, axis=1)
        shifted = jnp.pad(cs, ((0, 0), (R, 0)))[:, :cs.shape[1]]
        return cs - shifted

    def firing_compact(swin, m_k, win_next, pane_base):
        done = pane_base + m_k
        n_fired = jnp.maximum(0, (done - win_next) // 1 + 1)
        run = jnp.cumsum(n_fired)
        MAXO = CAP // Pn + 2 * K + 8
        slot = jnp.arange(MAXO)
        owner = jnp.searchsorted(run, slot, side="right")
        owner_c = jnp.minimum(owner, K - 1)
        base = jnp.where(owner_c > 0, run[owner_c - 1], 0)
        j = slot - base
        col = jnp.clip(win_next[owner_c] + j - pane_base[owner_c],
                       0, swin.shape[1] - 1)
        vals = swin[owner_c, col]
        return vals, owner_c, (slot < run[K - 1])

    return {
        "key_extract_argsort": key_extract_argsort,
        "grouping_rank_scatter": grouping_rank_scatter,
        "sort_gather": sort_gather,
        "rank_scan": rank_scan,
        "rank_hist": rank_hist,
        "pane_cells": pane_cells,
        "sliding_fold": sliding_fold,
        "sliding_fold_plain": sliding_fold_plain,
        "sliding_fold_cumsum": sliding_fold_cumsum,
        "firing_compact": firing_compact,
    }, NP1


def _identity_stubs(region: str):
    """(module attr name -> stub) map swapping ONE step region for an
    identity of the same output shapes — covers both the lax bodies
    (windows/ffat_kernels) and the Pallas twins (windflow_tpu/kernels)
    so the ablation composes with either build."""
    import jax.numpy as jnp

    from windflow_tpu import kernels as pk
    from windflow_tpu.windows import ffat_kernels as fk
    if region == "grouping":
        def order_hist_stub(ids, nb, grouping=None, pallas=None):
            n = ids.shape[0]
            return (jnp.arange(n, dtype=jnp.int32),
                    jnp.zeros(nb, jnp.int32).at[ids].add(1))

        def rank_hist_stub(ids, nb, interpret):
            n = ids.shape[0]
            z = jnp.zeros(n, jnp.int32)
            return z, z, jnp.zeros(nb, jnp.int32).at[ids].add(1)

        def dense_rank_stub(ids, nb):
            n = ids.shape[0]
            z = jnp.zeros(n, jnp.int32)
            return (z, jnp.zeros(nb, jnp.int32).at[ids].add(1)[:nb],
                    ids, jnp.arange(n, dtype=jnp.int32))

        return {(fk, "_group_order_hist"): order_hist_stub,
                (fk, "_group_order"):
                    lambda ids, nb, g, pallas=None:
                        jnp.arange(ids.shape[0], dtype=jnp.int32),
                (fk, "dense_rank"): dense_rank_stub,
                (pk, "grouping_rank_hist"): rank_hist_stub,
                (pk, "order_hist"):
                    lambda ids, nb, interpret:
                        order_hist_stub(ids, nb)}
    if region == "pane_scan":
        return {(fk, "_seg_scan"): lambda comb, flags, values: values}
    if region == "sliding_fold":
        return {(fk, "_sliding_reduce"):
                    lambda comb, flags, values, R, axis: (flags, values),
                (fk, "_sliding_reduce_plain"):
                    lambda comb, flags, values, R, axis, monoid: values,
                (pk, "sliding_fold"):
                    lambda values, valid, R, monoid, interpret: values}
    raise ValueError(region)


def _time_step(jax, step, state, payload, ts, valid, steps):
    st, out, fired, _ = step(state, payload, ts, valid)
    jax.block_until_ready(st)
    import time as _time
    rates = []
    for _ in range(5):
        t0 = _time.perf_counter()
        s = st
        for _ in range(steps):
            s, out, fired, _ = step(s, payload, ts, valid)
        jax.block_until_ready(s)
        rates.append((_time.perf_counter() - t0) / steps)
    rates.sort()
    return rates[len(rates) // 2]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    dev = jax.devices()[0]
    platform = dev.platform
    # bench.py TPU config shapes (kept identical so the shares transfer)
    if platform == "tpu":
        CAP, K, WIN, SLIDE = 262144, 1024, 1024, 128
    else:
        CAP, K, WIN, SLIDE = 65536, 256, 1024, 128
    Pn = math.gcd(WIN, SLIDE)
    R, D = WIN // Pn, SLIDE // Pn

    comps, NP1 = build_components(jax, jnp, CAP, K, Pn, R)

    rng = np.random.default_rng(0)
    payload = {"k": jax.device_put(
                   jnp.asarray(rng.integers(0, K, CAP), jnp.int32), dev),
               "v": jax.device_put(
                   jnp.asarray(rng.random(CAP, dtype=np.float32)), dev)}
    valid = jax.device_put(jnp.ones(CAP, bool), dev)

    # pre-materialize stage inputs so each component times ONLY itself
    sk_sorted, v_sorted = jax.jit(comps["sort_gather"])(payload, valid)
    rank = jax.jit(comps["rank_scan"])(sk_sorted)
    pane_rel = (rank // Pn).astype(jnp.int32)
    cells = jax.jit(comps["pane_cells"])(sk_sorted, v_sorted, pane_rel)
    cell_has = cells != 0
    m_k = jnp.full(K, NP1 - 2, jnp.int32)
    win_next = jnp.zeros(K, jnp.int64)
    pane_base = jnp.zeros(K, jnp.int64)
    jax.block_until_ready(cells)

    arg_map = {
        "key_extract_argsort": (payload, valid),
        "grouping_rank_scatter": (payload, valid),
        "sort_gather": (payload, valid),
        "rank_scan": (sk_sorted,),
        "rank_hist": (payload, valid, sk_sorted),
        "pane_cells": (sk_sorted, v_sorted, pane_rel),
        "sliding_fold": (cells, cell_has),
        "sliding_fold_plain": (cells, cell_has),
        "sliding_fold_cumsum": (cells, cell_has),
        "firing_compact": (jnp.pad(cells, ((0, 0), (R - 1, 0))), m_k,
                           win_next, pane_base),
    }

    def time_fn(fn, fargs):
        jfn = jax.jit(fn)
        out = jfn(*fargs)
        jax.block_until_ready(out)
        rates = []
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(args.steps):
                out = jfn(*fargs)
            jax.block_until_ready(out)
            rates.append((time.perf_counter() - t0) / args.steps)
        rates.sort()
        return rates[len(rates) // 2]

    # full step reference points (the bench kernel), one per grouping
    from windflow_tpu.windows.ffat_kernels import (make_ffat_state,
                                                   make_ffat_step)
    ts = jax.device_put(jnp.arange(CAP, dtype=jnp.int64), dev)
    full_by_grouping = {}
    for grouping in ("rank_scatter", "argsort"):
        step = jax.jit(make_ffat_step(CAP, K, Pn, R, D, lambda x: x["v"],
                                      lambda a, b: a + b,
                                      lambda x: x["k"], grouping=grouping))
        state = jax.device_put(
            make_ffat_state(jnp.zeros((), jnp.float32), K, R), dev)

        def full(state):
            st, out, fired, _ = step(state, payload, ts, valid)
            return st

        st = full(state)
        jax.block_until_ready(st)
        rates = []
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(args.steps):
                st = full(st)
            jax.block_until_ready(st)
            rates.append((time.perf_counter() - t0) / args.steps)
        rates.sort()
        full_by_grouping[grouping] = rates[len(rates) // 2]
    full_s = full_by_grouping["rank_scatter"]

    result = {
        "platform": platform, "device": str(dev),
        "config": {"cap": CAP, "keys": K, "win": WIN, "slide": SLIDE,
                   "panes": NP1, "R": R},
        "full_step_ms": round(full_s * 1e3, 4),
        "full_step_tuples_per_sec": round(CAP / full_s, 1),
        "full_step_argsort_ms": round(
            full_by_grouping["argsort"] * 1e3, 4),
        "full_step_argsort_tuples_per_sec": round(
            CAP / full_by_grouping["argsort"], 1),
        "rank_scatter_speedup": round(
            full_by_grouping["argsort"] / full_s, 4),
        "components_ms": {},
        "note": ("components are timed standalone; inside the fused step "
                 "XLA overlaps/fuses them, so shares are indicative; "
                 "full_step uses grouping=rank_scatter, "
                 "full_step_argsort the comparison-sort baseline"),
    }
    for name, fn in comps.items():
        t = time_fn(fn, arg_map[name])
        result["components_ms"][name] = {
            "ms": round(t * 1e3, 4),
            "pct_of_full": round(100 * t / full_s, 1),
        }

    # -- in-fused-step ablation (the r05 "shares are indicative" honesty
    # fix): swap ONE region for an identity stub, re-time the WHOLE
    # step; full - ablated is the region's REAL fused-step share --------
    def build_and_time(monoid=None, pallas=None, stubs=None, steps=None):
        saved = {}
        if stubs:
            for key, fn in stubs.items():
                saved[key] = getattr(key[0], key[1])
                setattr(key[0], key[1], fn)
        try:
            # stubs must stay live through the first dispatch: the jit
            # traces the module seams lazily, so timing happens inside
            # the patch window
            step = jax.jit(make_ffat_step(
                CAP, K, Pn, R, D, lambda x: x["v"], lambda a, b: a + b,
                lambda x: x["k"], monoid=monoid, pallas=pallas))
            state = jax.device_put(
                make_ffat_state(jnp.zeros((), jnp.float32), K, R), dev)
            return _time_step(jax, step, state, payload, ts, valid,
                              steps or args.steps)
        finally:
            for key, fn in saved.items():
                setattr(key[0], key[1], fn)

    result["ablation_ms"] = {}
    for region in ("grouping", "pane_scan", "sliding_fold"):
        t = build_and_time(stubs=_identity_stubs(region))
        result["ablation_ms"][region] = {
            "ablated_step_ms": round(t * 1e3, 4),
            "attributed_ms": round((full_s - t) * 1e3, 4),
            "attributed_pct_of_full": round(100 * (full_s - t) / full_s,
                                            1),
        }
    result["ablation_note"] = (
        "attributed_ms = full_step_ms - step_ms with the region swapped "
        "for an identity stub INSIDE the fused step — the real "
        "fused-step share a kernel win is judged against (standalone "
        "components_ms over-count by the XLA overlap)")

    # -- Pallas comparison block (docs/PERF.md round 14) ----------------
    from windflow_tpu.basic import Config as _Config
    from windflow_tpu.kernels import resolve_pallas
    pmode = resolve_pallas(_Config())
    pcomp = {
        "backend": platform,
        "kernels_selected": pmode is not None,
        "interpret_mode": (bool(pmode.interpret) if pmode is not None
                           else None),
        "note": ("interpret_mode=true means the kernels run under the "
                 "Pallas interpreter (CPU tier-1 correctness vehicle) — "
                 "expected SLOWER than lax; real speedups are compiled "
                 "TPU numbers"),
    }
    if pmode is not None:
        psteps = min(args.steps, 5) if pmode.interpret else args.steps
        for label, monoid in (("generic", None), ("monoid_sum", "sum")):
            t_lax = build_and_time(monoid=monoid, steps=psteps)
            t_pal = build_and_time(monoid=monoid, pallas=pmode,
                                   steps=psteps)
            pcomp[label] = {
                "lax_step_ms": round(t_lax * 1e3, 4),
                "pallas_step_ms": round(t_pal * 1e3, 4),
                "ffat_step_speedup_vs_lax": round(t_lax / t_pal, 4),
            }
    result["pallas_compare"] = pcomp
    line = json.dumps(result, indent=2)
    print(line)
    if args.json:
        with open(args.json, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
