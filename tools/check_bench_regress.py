#!/usr/bin/env python
"""Run-over-run perf tripwire on bench_history.json.

``tools/check_bench_keys.py`` guards that the bench still EMITS its
contract keys; nothing guarded their VALUES — a hop that got 30% slower
sailed through CI as long as the key existed.  This check compares the
newest ``bench_history.json`` run per platform against the most recent
earlier run recorded under the SAME methodology (and, for e2e legs, the
same tuple count — CI runs the bench reduced) and trips on any guarded
scalar moving more than the threshold in the bad direction.

Under ``CI=1`` a regression fails (exit 1); locally it warns (exit 0),
because a laptop run racing a browser is not a regression.  Noise is
respected twice over: a key whose own recorded dispersion
(``rel_spread``) exceeds the threshold on either side of the comparison
is reported but never tripped — when the measurement's noise floor is
above the tripwire, the tripwire would only fire on weather — and a key
whose TRAILING HISTORY (the last same-methodology comparable runs)
already spreads wider than the threshold is likewise reported, not
tripped: within-run dispersion systematically understates run-to-run
variance on a shared box (five windows seconds apart share the same
weather; runs hours apart do not), and a key that historically swings
2x with no code change cannot honestly gate a 10% move.  Deterministic
keys (checkpoint bytes, seeded skew ratios) have flat histories and
stay hard-guarded.

Usage::

    python tools/check_bench_regress.py             # newest run, each
                                                    # platform in history
    python tools/check_bench_regress.py --platform cpu
    WF_BENCH_REGRESS_PCT=15 python tools/check_bench_regress.py

Wired into ``ci/run_tests.sh`` directly after the bench leg (which has
just appended the run under judgment).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HISTORY = os.path.join(REPO, "bench_history.json")

#: guarded scalars: (dotted path, higher_is_better, dispersion path or
#: None).  Dispersion gates the tripwire on that key's own noise floor.
GUARDED = (
    ("value", True, "dispersion.rel_spread"),
    ("dispatch_value", True, "dispatch_dispersion.rel_spread"),
    # sum_decl records no dispersion of its own; the chained kernel's
    # spread is the same program on the same machine minutes apart —
    # the honest noise proxy.  Same for the latency tails below: a p99
    # measured while the kernel windows spread 2x is weather.
    ("sum_decl_value", True, "dispersion.rel_spread"),
    ("e2e.tuples_per_sec", True, "e2e.dispersion.rel_spread"),
    ("e2e_device_source.tuples_per_sec", True,
     "e2e_device_source.dispersion.rel_spread"),
    ("reduce.sorted_tps", True, "reduce.sorted_dispersion.rel_spread"),
    ("reduce.dense_decl_tps", True,
     "reduce.dense_decl_dispersion.rel_spread"),
    ("latency.batch_p99_ms", False, "dispersion.rel_spread"),
    ("latency.e2e_p99_ms", False, "e2e.dispersion.rel_spread"),
    # durability plane: snapshot size is deterministic for a fixed
    # graph/cadence, so a >10% jump is a real regression (a new state
    # blob grew), not weather.  checkpoint_ms and overhead_pct are
    # deliberately NOT value-guarded here: both are short wall
    # measurements (checkpoint_ms includes an fsync; overhead_pct is the
    # ratio of two single-shot runs) whose infra jitter exceeds the
    # threshold, and no recorded dispersion describes them — the
    # overhead's hard budget lives in check_bench_keys instead.
    ("durability.checkpoint_bytes", False, None),
    # shard plane: the bench leg's stream is SEEDED, so the measured
    # imbalance and hot-key share are deterministic — any >10% move is
    # a sketch/placement regression, not weather.  Both directions
    # matter, but the ratios only drift DOWN when the sketch starts
    # losing counts, which is the failure mode worth tripping on.
    ("shard.imbalance_ratio", True, None),
    ("shard.hot_key_share", True, None),
    # key compaction: the whole round's reason to exist is the ratio —
    # compacted over sorted, measured as the median of PAIRED windows
    # (each round times both legs under the same instantaneous load),
    # so the ratio's own recorded spread is the honest noise gate.
    # hit_rate's hard 0.9 floor lives in check_bench_keys; this guards
    # the SPEED.
    ("compaction.speedup_vs_sorted", True,
     "compaction.speedup_dispersion.rel_spread"),
    # wire plane: the leg's stream is SEEDED and EVENT-timed, so the
    # measured wire bytes/tuple is deterministic — a >10% rise means a
    # codec stopped engaging (selection, fit check, or the dict union
    # broke), not weather.  LOWER is better.  compression_ratio's hard
    # 1.5x floor lives in check_bench_keys; this guards the trend.
    ("wire.wire_bytes_per_tuple", False, None),
    # reshard executor: keys_moved is fully deterministic on the seeded
    # colocated-warm-pair stream (trigger → advisor plan → apply), so
    # any change is a planner/trigger regression.  plan_apply_ms /
    # rescale_restore_ms are deliberately NOT guarded: both are short
    # single-shot wall measurements (the apply includes a full graph
    # quiesce, the restore an fsynced store replay) whose infra jitter
    # exceeds the threshold — their sanity bounds live in
    # check_bench_keys.
    ("reshard.keys_moved", True, None),
    # pallas kernels: the fused-step kernel-vs-lax ratio is the round's
    # headline (docs/PERF.md round 14).  Comparable only between runs
    # with the SAME interpret_mode (a compiled-TPU speedup and a
    # CPU-interpreter emulation measure different things — the
    # comparable() gate below); correctness has its own hard guard
    # (record_mismatch, check_bench_keys).
    ("pallas.ffat_step_speedup_vs_lax", True, None),
    ("pallas.grouping_speedup", True, None),
    # latency plane: the ledger-decomposed staged->sunk p99 at max
    # sustainable throughput (docs/OBSERVABILITY.md "Latency plane &
    # SLO") — LOWER is better.  A whole-pipeline wall tail on a shared
    # box has no recorded dispersion of its own, so the trailing-history
    # spread gate below is the honest noise floor; the hard bound (p99
    # past 2x the recorded SLO budget) lives in check_bench_keys.
    ("latency_slo.e2e_p99_ms", False, None),
    # megastep executor: the K-folded staged e2e rate is round 15's
    # headline (docs/PERF.md round 15) and the speedup over the K=1
    # kill switch is the claim the fold exists for — both gated on the
    # K-run's own recorded spread (a whole-pipeline wall measurement
    # on a shared box).  The hard floors (absolute CPU rate, the
    # 1-program-per-K-sweeps dispatch pin) live in check_bench_keys;
    # this guards the trend.
    ("megastep.e2e_tup_s", True, "megastep.dispersion.rel_spread"),
    ("megastep.speedup_vs_k1", True, "megastep.dispersion.rel_spread"),
    # tenant plane: the two-tenant leg is SEEDED, so the attributed
    # fraction is deterministic — any drop means the ledger stopped
    # reconciling (a new staging path it does not see, or a register
    # baseline bug), not weather.  HIGHER is better; the hard 0.9 floor
    # and the 2% overhead budget live in check_bench_keys — this guards
    # the trend.
    ("tenant.hbm_attributed_fraction", True, None),
)


def dig(obj, path: str):
    for part in path.split("."):
        if not isinstance(obj, dict):
            return None
        obj = obj.get(part)
    return obj


def comparable(cur: dict, prev: dict, path: str) -> bool:
    """Apples-to-apples guard: e2e legs only compare runs that pushed
    the same tuple count (CI runs the bench reduced via
    BENCH_E2E_TUPLES; comparing a 131k-tuple run against a 4M-tuple
    round would trip on configuration, not performance)."""
    # hardware gate first (docs/OBSERVABILITY.md "Calibration plane"):
    # rows recorded on different backends or device kinds measure
    # different machines, whatever the leg.  A MISSING stamp is a
    # wildcard — history predating the stamp stays comparable; only a
    # PRESENT-and-different stamp refuses.
    for stamp in ("backend", "device_kind"):
        a, b = cur.get(stamp), prev.get(stamp)
        if a is not None and b is not None and a != b:
            return False
    if path.startswith(("e2e.", "e2e_device_source.", "latency.e2e")):
        leg = "e2e_device_source" if path.startswith("e2e_device_source") \
            else "e2e"
        return dig(cur, f"{leg}.tuples") == dig(prev, f"{leg}.tuples")
    if path.startswith("durability."):
        # the durability leg sizes via BENCH_DURABILITY_TUPLES: different
        # stream lengths checkpoint different state — not comparable
        return dig(cur, "durability.tuples") == dig(prev,
                                                    "durability.tuples")
    if path.startswith("shard."):
        # the shard leg's skew numbers are seeded per tuple count
        # (BENCH_SHARD_TUPLES): a different stream is a different truth
        return dig(cur, "shard.tuples") == dig(prev, "shard.tuples")
    if path.startswith("wire."):
        # the wire leg is seeded per tuple count AND window spec (codec
        # choice sees the spec's lanes): only identical streams compare
        return dig(cur, "wire.tuples") == dig(prev, "wire.tuples")
    if path.startswith("reshard."):
        # the reshard leg's move count is seeded per tuple count
        # (BENCH_RESHARD_TUPLES): a different stream plans differently
        return dig(cur, "reshard.tuples") == dig(prev, "reshard.tuples")
    if path.startswith("pallas."):
        # interpret-mode (CPU emulated) and compiled-TPU kernel numbers
        # are different experiments; only like compares with like
        return dig(cur, "pallas.interpret_mode") == \
            dig(prev, "pallas.interpret_mode")
    if path.startswith("latency_slo."):
        # the latency-SLO leg is sized via BENCH_SLO_TUPLES and its tail
        # only compares at the SAME operating point: a different stream
        # length or label measures a different experiment
        return dig(cur, "latency_slo.tuples") == \
            dig(prev, "latency_slo.tuples") \
            and dig(cur, "latency_slo.operating_point") == \
            dig(prev, "latency_slo.operating_point")
    if path.startswith("tenant."):
        # the tenant leg is seeded per tuple count (BENCH_TENANT_TUPLES):
        # a different stream stages different bytes to reconcile
        return dig(cur, "tenant.tuples") == dig(prev, "tenant.tuples")
    if path.startswith("compaction."):
        # the compaction A/B is seeded per batch width (cfg["cap"]):
        # a different stream shape shifts the hot-set/overflow split
        # and with it the honest speedup
        return dig(cur, "compaction.tuples") == dig(prev,
                                                    "compaction.tuples")
    return True


def pick_baseline(runs: list, cur: dict):
    """Most recent run BEFORE the newest one with the same methodology
    (a methodology switch re-baselines, exactly like bench.py's
    vs_baseline); None when the newest run is the first of its kind."""
    prior = runs[:-1]
    same = [r for r in prior
            if r.get("methodology") == cur.get("methodology")]
    return same[-1] if same else None


#: trailing-history noise floor: how many prior same-methodology runs
#: to consider, and how many are needed before history can vouch for a
#: key (younger keys stay hard-guarded)
HISTORY_WINDOW = 8
HISTORY_MIN = 3


def history_spread(runs: list, cur: dict, path: str):
    """Relative spread ((max-min)/mean) of the guarded scalar over the
    trailing window of same-methodology comparable runs BEFORE the run
    under judgment; None when history is too short to vouch."""
    vals = []
    for r in runs[:-1]:
        if r.get("methodology") != cur.get("methodology"):
            continue
        if not comparable(cur, r, path):
            continue
        v = dig(r, path)
        if isinstance(v, (int, float)) and v:
            vals.append(float(v))
    vals = vals[-HISTORY_WINDOW:]
    if len(vals) < HISTORY_MIN:
        return None
    mean = sum(vals) / len(vals)
    return (max(vals) - min(vals)) / mean if mean else None


def check_platform(platform: str, runs: list, threshold: float) -> list:
    """[(path, change_pct, kind)] where kind is "regression" | "noisy"
    (own recorded dispersion above threshold) | "noisy_history"
    (trailing run-over-run spread above threshold)."""
    if len(runs) < 2:
        return []
    cur = runs[-1]
    prev = pick_baseline(runs, cur)
    if prev is None:
        return []
    findings = []
    for path, higher_better, disp_path in GUARDED:
        a, b = dig(prev, path), dig(cur, path)
        if not isinstance(a, (int, float)) \
                or not isinstance(b, (int, float)) or not a:
            continue
        if not comparable(cur, prev, path):
            continue
        change = (b - a) / a
        worse = -change if higher_better else change
        if worse <= threshold:
            continue
        noisy = False
        if disp_path is not None:
            for side in (cur, prev):
                spread = dig(side, disp_path)
                if isinstance(spread, (int, float)) \
                        and spread > threshold:
                    noisy = True
        kind = "regression"
        if noisy:
            kind = "noisy"
        else:
            hs = history_spread(runs, cur, path)
            if hs is not None and hs > threshold:
                kind = "noisy_history"
        findings.append((path, round(100 * change, 1), kind))
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--platform", help="judge one platform only "
                                       "(default: every platform with "
                                       ">= 2 recorded runs)")
    ap.add_argument("--history", default=HISTORY,
                    help="bench_history.json path")
    args = ap.parse_args(argv)
    threshold = float(os.environ.get("WF_BENCH_REGRESS_PCT", "10")) / 100.0
    strict = os.environ.get("CI") not in (None, "", "0")
    try:
        with open(args.history) as f:
            hist = json.load(f)
    except (OSError, ValueError) as e:
        print(f"check_bench_regress: FAIL: cannot read {args.history}: "
              f"{e}", file=sys.stderr)
        return 1
    platforms = [args.platform] if args.platform else sorted(hist)
    tripped = False
    for platform in platforms:
        runs = hist.get(platform)
        if not isinstance(runs, list):
            continue
        findings = check_platform(platform, runs, threshold)
        for path, pct, kind in findings:
            if kind == "noisy":
                print(f"check_bench_regress: note [{platform}] {path} "
                      f"moved {pct:+}% but its recorded dispersion "
                      f"exceeds the {threshold:.0%} threshold — noise "
                      "floor, not tripped")
            elif kind == "noisy_history":
                print(f"check_bench_regress: note [{platform}] {path} "
                      f"moved {pct:+}% but its trailing run-over-run "
                      f"spread already exceeds the {threshold:.0%} "
                      "threshold — historical noise floor, not tripped")
            else:
                tripped = True
                print(f"check_bench_regress: "
                      f"{'FAIL' if strict else 'WARN'} [{platform}] "
                      f"{path} regressed {pct:+}% vs the previous "
                      f"same-methodology run (threshold "
                      f"{threshold:.0%})",
                      file=sys.stderr if strict else sys.stdout)
        if not findings:
            print(f"check_bench_regress: OK [{platform}] — no guarded "
                  f"key moved more than {threshold:.0%} the wrong way")
    if tripped and strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
