#!/usr/bin/env python
"""wf_chaos: failure-injection harness for the durability plane.

Runs the chaos matrix (graph family x kill point x fusion on/off): for
each cell, an uninterrupted baseline and a killed-then-restored run
over identical input, diffed record for record — the executable proof
of the exactly-once contract (docs/DURABILITY.md; the same cells back
``tests/test_durability.py``).

Usage::

    python tools/wf_chaos.py                          # default matrix
    python tools/wf_chaos.py --family window_tb --point mid_sink_flush
    python tools/wf_chaos.py --fusion off --records 8192 --json

Exit 1 when any cell diverges (loss, duplication, or reordering), with
the first divergence printed.  Everything runs in-process against the
in-memory broker — kills are simulated crashes; broker, checkpoint
store, and sink files survive as the external world.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_cell(family: str, point: str, fusion: bool, records: int,
             workdir: str) -> dict:
    from windflow_tpu.durability import chaos
    tag = f"{family}_{point}_{'on' if fusion else 'off'}"
    base = chaos.make_cell(
        family, os.path.join(workdir, tag, "ckpt_a"), fusion=fusion,
        out_dir=os.path.join(workdir, tag, "out_a"), n=records)
    chal = chaos.make_cell(
        family, os.path.join(workdir, tag, "ckpt_b"), fusion=fusion,
        out_dir=os.path.join(workdir, tag, "out_b"), n=records)
    verdict = chaos.run_ab(base["factory"], chal["factory"],
                           chaos.default_kill(family, point),
                           base["read"], chal["read"])
    verdict.update(family=family, point=point, fusion=fusion)
    return verdict


def run_rescale_cells(families, records: int, workdir: str,
                      with_mesh: bool) -> list:
    """Kill-a-shard / restore-on-N±1 cells: each rescale family is
    killed at its seeded shard count and restored on one fewer AND one
    more shard; mesh families additionally restore across mesh shapes
    (needs ≥4 visible devices — set
    XLA_FLAGS=--xla_force_host_platform_device_count=8 on CPU)."""
    from windflow_tpu.durability import chaos
    out = []
    for family in families:
        for shards_restore in (2, 4):       # kill at 3: N-1 and N+1
            out.append(chaos.run_rescale_ab(
                family, "mid_epoch", workdir, shards_kill=3,
                shards_restore=shards_restore, n=records))
    if with_mesh:
        from windflow_tpu.parallel.mesh import make_mesh
        for family in chaos.MESH_RESCALE_FAMILIES:
            for kk_kill, kk_restore in ((4, 2), (2, 4)):
                out.append(chaos.run_rescale_ab(
                    family, "mid_epoch", workdir, shards_kill=1,
                    shards_restore=1, mesh_kill=make_mesh(kk_kill),
                    mesh_restore=make_mesh(kk_restore), n=records))
    return out


def main(argv=None) -> int:
    from windflow_tpu.durability.chaos import (DETERMINISM_FAMILIES,
                                               FAMILIES, KILL_POINTS,
                                               RESCALE_FAMILIES)
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--family", choices=FAMILIES + DETERMINISM_FAMILIES,
                    action="append",
                    help="graph family (repeatable; default: every "
                         "exactly-once family — the determinism-"
                         "violating families are expected-fail-dynamic "
                         "and must be named explicitly)")
    ap.add_argument("--point", choices=KILL_POINTS, action="append",
                    help="kill point (repeatable; default: all)")
    ap.add_argument("--fusion", choices=("on", "off", "both"),
                    default="both")
    ap.add_argument("--records", type=int, default=4096)
    ap.add_argument("--rescale", choices=("on", "off"), default="on",
                    help="also run the kill-a-shard / restore-on-N±1 "
                         "rescale cells (per-key record diff)")
    ap.add_argument("--workdir", default=None,
                    help="directory for checkpoint stores / sink files "
                         "(default: a fresh tempdir)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    families = args.family or list(FAMILIES)
    points = args.point or list(KILL_POINTS)
    fusions = {"on": [True], "off": [False],
               "both": [True, False]}[args.fusion]
    workdir = args.workdir or tempfile.mkdtemp(prefix="wf_chaos_")
    results, failed = [], 0
    for family in families:
        for point in points:
            for fusion in fusions:
                v = run_cell(family, point, fusion, args.records, workdir)
                results.append(v)
                if family in DETERMINISM_FAMILIES:
                    # expected-fail-dynamic, caught-static: the cell
                    # exists to PROVE the replay diverges — holding
                    # exactly-once here would mean the seeded violation
                    # stopped violating (and wfverify's WF61x fixture
                    # with it)
                    ok = v["diff"] is not None
                    v["expected_fail_dynamic"] = True
                    failed += 0 if ok else 1
                    if not args.json:
                        if ok:
                            print(f"XFAIL {family:<15} {point:<15} "
                                  f"fusion={'on ' if fusion else 'off'} "
                                  "diverged as seeded (caught static: "
                                  "wfverify WF61x)")
                        else:
                            print(f"FAIL {family}: determinism cell "
                                  "held exactly-once — the seeded "
                                  "violation is gone")
                    continue
                ok = v["diff"] is None
                failed += 0 if ok else 1
                if not args.json:
                    print(f"{'OK  ' if ok else 'FAIL'} {family:<16} "
                          f"{point:<15} fusion={'on ' if fusion else 'off'}"
                          f" records={v['records']:<6} "
                          f"restored_epoch={v['restored_epoch']} "
                          f"dedupe={v['dedupe_hits']}"
                          + ("" if ok else f"\n     {v['diff']}"))
    if args.rescale == "on":
        import jax
        rescale_fams = [f for f in RESCALE_FAMILIES if f in families]
        if args.family and not rescale_fams:
            print("wf_chaos: none of the selected families "
                  f"({families}) has a rescale cell "
                  f"(rescale families: {list(RESCALE_FAMILIES)})",
                  file=sys.stderr)
        # mesh cells ride only the FULL matrix: a named-family run is a
        # targeted replica-rescale repro
        with_mesh = not args.family and len(jax.devices()) >= 4
        if not args.family and not with_mesh:
            print("wf_chaos: <4 devices visible — skipping the mesh "
                  "rescale cells (set XLA_FLAGS="
                  "--xla_force_host_platform_device_count=8)",
                  file=sys.stderr)
        for v in run_rescale_cells(rescale_fams, args.records, workdir,
                                   with_mesh):
            results.append(v)
            ok = v["diff"] is None
            failed += 0 if ok else 1
            if not args.json:
                shape = v["mesh"] or v["shards"]
                print(f"{'OK  ' if ok else 'FAIL'} "
                      f"{v['family']:<16} {v['point']:<15} "
                      f"rescale={shape:<8} records={v['records']:<6} "
                      f"restored_epoch={v['restored_epoch']}"
                      + ("" if ok else f"\n     {v['diff']}"))
    if args.json:
        json.dump(results, sys.stdout, indent=1)
        print()
    n_det = sum(1 for v in results if v.get("expected_fail_dynamic"))
    n_rescale = sum(1 for v in results if v.get("rescale"))
    n_eo = len(results) - n_det - n_rescale
    if failed:
        print(f"wf_chaos: FAIL — {failed}/{len(results)} cell(s) "
              "violated their contract (exactly-once cells must hold; "
              "determinism cells must diverge as seeded)",
              file=sys.stderr)
        return 1
    print(f"wf_chaos: OK — {n_eo} cell(s) held exactly-once"
          + (f", {n_rescale} rescale (kill-a-shard / restore-on-N±1) "
             "cell(s) held per-key exact" if n_rescale else "")
          + (f", {n_det} determinism cell(s) diverged as seeded"
             if n_det else "")
          + f" (workdir {workdir})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
