#!/usr/bin/env python
"""wf_lint: pure-AST lint enforcing windflow_tpu's hot-path invariants.

PRs 1-2 established three hot-path rules by convention — no allocation,
no host synchronization, no lock acquisition on the staging pack loop,
the flight-recorder ring writes, and the emitter/collector service
loops.  Functions carrying the ``@hot_path`` mark
(``windflow_tpu/analysis/hotpath.py``) now get them enforced statically,
alongside two repo-wide hygiene rules.  Pure ``ast`` — no imports of the
package, no jax, so the whole tree lints in well under ten seconds.

Rules (codes from ``windflow_tpu/analysis/diagnostics.py``):

* **WF701** allocation in ``@hot_path``: ``np.zeros``-family /
  ``np.concatenate``-family calls, ``list()``/``dict()``/``set()``
  calls, list/set/dict comprehensions.  Small literals are allowed.
* **WF702** host sync in ``@hot_path``: ``np.asarray``,
  ``.block_until_ready()``, ``jax.device_get`` /
  ``jax.block_until_ready``.
* **WF703** lock acquisition in ``@hot_path``: ``with ...lock...`` or
  ``.acquire()``.
* **WF711** bare ``except:`` anywhere.
* **WF712** broad ``except Exception``/``BaseException`` anywhere,
  unless justified inline with a ``lint: broad-except-ok (reason)``
  comment on (or within two lines below) the ``except`` line.
* **WF721** declared-lock discipline: a class declaring
  ``__lock_guards__ = {"_lock": ("attr", ...)}`` promises those
  ``self`` attributes are only touched inside ``with self._lock``
  (``__init__`` construction excepted).

Usage::

    python tools/wf_lint.py                  # lint windflow_tpu/
    python tools/wf_lint.py PATH [PATH...]   # lint specific files/trees
    python tools/wf_lint.py --json           # machine-readable findings

Exit status 1 when any violation is found (the CI gate runs this).
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_PATHS = [os.path.join(REPO, "windflow_tpu")]

#: np/jnp allocator calls banned on hot paths
ALLOC_ATTRS = {
    "zeros", "ones", "empty", "full", "zeros_like", "ones_like",
    "empty_like", "full_like", "concatenate", "stack", "vstack",
    "hstack", "arange", "array", "tile",
}
NP_NAMES = {"np", "numpy", "jnp"}
#: builder calls banned on hot paths (literals stay allowed)
ALLOC_BUILDERS = {"list", "dict", "set"}
#: host-sync calls banned on hot paths, any receiver
SYNC_ANY = {"block_until_ready", "device_get"}
#: host-sync calls banned on hot paths when called on np/numpy/jnp
SYNC_NP = {"asarray"}
#: substring that justifies a broad except within 2 lines of the handler
ALLOW_BROAD = "lint: broad-except-ok"


def _finding(path: str, node, code: str, message: str,
             hint: Optional[str] = None) -> dict:
    return {
        "code": code,
        "severity": "error",
        "message": message,
        "node": None,
        "location": f"{os.path.relpath(path, REPO)}:{node.lineno}",
        "hint": hint,
    }


def _is_hot_path_deco(dec) -> bool:
    if isinstance(dec, ast.Name):
        return dec.id == "hot_path"
    if isinstance(dec, ast.Attribute):
        return dec.attr == "hot_path"
    return False


def _receiver_name(func) -> Optional[str]:
    """Name of the object a method is called on: ``np`` for
    ``np.zeros(...)``, None for plain calls."""
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return func.value.id
    return None


def _lockish(expr) -> bool:
    """A with-context expression that smells like a lock: any name/attr
    containing "lock" (``self._lock``, ``self._inflight_lock``, a bare
    ``lock``), or an explicit ``.acquire()``/``.lock()`` call."""
    if isinstance(expr, ast.Call):
        return _lockish(expr.func)
    if isinstance(expr, ast.Attribute):
        return "lock" in expr.attr.lower() or _lockish(expr.value)
    if isinstance(expr, ast.Name):
        return "lock" in expr.id.lower()
    return False


def _check_hot_function(path: str, fn, findings: List[dict]) -> None:
    name = fn.name
    for node in ast.walk(fn):
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp)):
            findings.append(_finding(
                path, node, "WF701",
                f"@hot_path function '{name}' builds a comprehension",
                hint="preallocate outside the hot path or stream through "
                     "an existing buffer"))
        elif isinstance(node, ast.Call):
            recv = _receiver_name(node.func)
            attr = node.func.attr if isinstance(node.func, ast.Attribute) \
                else None
            callee = node.func.id if isinstance(node.func, ast.Name) \
                else None
            if callee in ALLOC_BUILDERS:
                findings.append(_finding(
                    path, node, "WF701",
                    f"@hot_path function '{name}' calls {callee}() — "
                    "allocation on the hot path",
                    hint="hoist the container to construction time"))
            elif attr in ALLOC_ATTRS and recv in NP_NAMES:
                findings.append(_finding(
                    path, node, "WF701",
                    f"@hot_path function '{name}' calls {recv}.{attr} — "
                    "array allocation on the hot path",
                    hint="recycle a pooled/preallocated buffer "
                         "(windflow_tpu/staging.py)"))
            elif attr in SYNC_ANY or (attr in SYNC_NP and recv in NP_NAMES):
                findings.append(_finding(
                    path, node, "WF702",
                    f"@hot_path function '{name}' calls "
                    f"{(recv + '.') if recv else '.'}{attr} — host "
                    "synchronization stalls the dispatch loop",
                    hint="keep device syncs on the sampled/diagnostic "
                         "paths only"))
            elif attr == "acquire" and _lockish(node.func.value):
                findings.append(_finding(
                    path, node, "WF703",
                    f"@hot_path function '{name}' acquires a lock",
                    hint="hot paths are single-consumer by construction; "
                         "move locking to the cold setup path"))
        elif isinstance(node, ast.With):
            for item in node.items:
                if _lockish(item.context_expr):
                    findings.append(_finding(
                        path, node, "WF703",
                        f"@hot_path function '{name}' acquires a lock "
                        "(with-block)",
                        hint="hot paths are single-consumer by "
                             "construction; move locking to the cold "
                             "setup path"))


def _check_excepts(path: str, tree, lines: List[str],
                   findings: List[dict]) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            findings.append(_finding(
                path, node, "WF711",
                "bare 'except:' swallows KeyboardInterrupt/SystemExit "
                "and masks real faults",
                hint="catch the specific exceptions the block can raise"))
            continue
        names = []
        for t in ([node.type.elts] if isinstance(node.type, ast.Tuple)
                  else [[node.type]]):
            for e in t:
                if isinstance(e, ast.Name):
                    names.append(e.id)
                elif isinstance(e, ast.Attribute):
                    names.append(e.attr)
        if not any(n in ("Exception", "BaseException") for n in names):
            continue
        # a broad handler whose LAST statement is a bare `raise` is a
        # cleanup trampoline (release resources, re-raise the original) —
        # it swallows nothing
        if node.body and isinstance(node.body[-1], ast.Raise) \
                and node.body[-1].exc is None:
            continue
        lo = node.lineno - 1
        window = "\n".join(lines[lo:lo + 3])
        if ALLOW_BROAD in window:
            continue
        findings.append(_finding(
            path, node, "WF712",
            "broad 'except Exception' without justification",
            hint="catch specific exceptions, or justify inline with a "
                 f"'{ALLOW_BROAD} (reason)' comment"))


class _GuardVisitor(ast.NodeVisitor):
    """Within one method of a __lock_guards__ class, track the with-stack
    and flag guarded-attribute touches outside their declared lock."""

    def __init__(self, path: str, cls_name: str, fn_name: str,
                 guards: dict, findings: List[dict]) -> None:
        self.path = path
        self.cls = cls_name
        self.fn = fn_name
        self.guards = guards        # attr -> lock attr
        self.findings = findings
        self.held: List[str] = []   # lock attrs currently held

    @staticmethod
    def _self_attr(expr) -> Optional[str]:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return expr.attr
        return None

    def visit_With(self, node: ast.With) -> None:
        locks = [a for item in node.items
                 for a in [self._self_attr(item.context_expr)]
                 if a is not None]
        self.held.extend(locks)
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - len(locks):]

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = self._self_attr(node)
        if attr in self.guards and self.guards[attr] not in self.held:
            self.findings.append(_finding(
                self.path, node, "WF721",
                f"{self.cls}.{self.fn} touches self.{attr} outside "
                f"'with self.{self.guards[attr]}' (declared in "
                "__lock_guards__)",
                hint="take the declared lock around every access, or "
                     "amend the declaration if the discipline changed"))
        self.generic_visit(node)


def _lock_guards_of(cls: ast.ClassDef) -> dict:
    """attr -> lock-attr map from a literal ``__lock_guards__``
    declaration; {} when the class declares none."""
    out = {}
    for stmt in cls.body:
        if isinstance(stmt, ast.Assign) \
                and any(isinstance(t, ast.Name)
                        and t.id == "__lock_guards__"
                        for t in stmt.targets) \
                and isinstance(stmt.value, ast.Dict):
            for k, v in zip(stmt.value.keys, stmt.value.values):
                if not isinstance(k, ast.Constant):
                    continue
                if isinstance(v, (ast.Tuple, ast.List)):
                    for e in v.elts:
                        if isinstance(e, ast.Constant):
                            out[e.value] = k.value
    return out


def _check_lock_guards(path: str, tree, findings: List[dict]) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        guards = _lock_guards_of(node)
        if not guards:
            continue
        for fn in node.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__":
                continue    # construction precedes sharing
            _GuardVisitor(path, node.name, fn.name, guards,
                          findings).visit(fn)


def lint_file(path: str) -> List[dict]:
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [{"code": "WF711", "severity": "error",
                 "message": f"cannot parse: {e}", "node": None,
                 "location": f"{os.path.relpath(path, REPO)}:"
                             f"{e.lineno or 0}", "hint": None}]
    lines = src.splitlines()
    findings: List[dict] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and any(_is_hot_path_deco(d) for d in node.decorator_list):
            _check_hot_function(path, node, findings)
    _check_excepts(path, tree, lines, findings)
    _check_lock_guards(path, tree, findings)
    return findings


def lint_paths(paths) -> List[dict]:
    findings: List[dict] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                for f in sorted(files):
                    if f.endswith(".py"):
                        findings.extend(lint_file(os.path.join(root, f)))
        else:
            findings.extend(lint_file(p))
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories (default: windflow_tpu/)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as a JSON array")
    args = ap.parse_args(argv)
    findings = lint_paths(args.paths or DEFAULT_PATHS)
    if args.json:
        print(json.dumps(findings, indent=2))
    else:
        for f in findings:
            hint = f" (hint: {f['hint']})" if f.get("hint") else ""
            print(f"{f['location']}: {f['code']} {f['message']}{hint}")
        print(f"wf_lint: {len(findings)} violation(s)"
              if findings else "wf_lint: OK (0 violations)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
