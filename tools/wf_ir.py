#!/usr/bin/env python
"""wf_ir: audit the lowered StableHLO of an application's programs.

CLI face of wfir (``windflow_tpu/analysis/ir_audit.py``), mirroring
``tools/wf_verify.py``: point it at the module that builds your
PipeGraph and every program the compile watcher has captured — plus a
dry lower of the user kernels when the graph never compiled — is audited
on the IR the chip actually runs: cross-chip collectives on edges the
aligned-ingest plan promised collective-free (WF901), host callbacks in
hot-path programs (WF902), 64-bit survivors on TPU (WF903), dynamic
shapes (WF904), donation misses (WF905), mid-program D2H syncs (WF906),
and Pallas kernels that lost their Mosaic custom call (WF907).

Usage::

    python tools/wf_ir.py APP_MODULE[:ATTR] [MORE...]
    python tools/wf_ir.py ... --drive 8192   # feed a seeded synthetic
                                             # stream into empty sources
                                             # and RUN each graph so its
                                             # real programs compile and
                                             # get audited
    python tools/wf_ir.py ... --json         # machine-readable
    python tools/wf_ir.py ... --strict       # exit 1 on warnings too

Verify-target factories (``tools/verify_targets.py``) compose their
graphs with empty sources (``lambda: iter(())``) — composition is all
wfverify needs, but an IR audit wants the LOWERED programs.  ``--drive``
closes that gap: any source whose generator yields nothing is given a
seeded synthetic generator derived from its declared record spec
(monotone ``id``/``ts`` lanes, small-domain ints for keys, uniform
floats), the graph runs to completion on the local backend, and the
audit then covers every program the run compiled.  Sources that already
produce data (the chaos cells) keep their own streams.

Inline suppressions (``# wfverify: ok (reason)`` on the kernel ``def``)
are shared with wfverify and counted.  Exit status: 0 clean, 1
error-severity findings (or any finding under ``--strict``), 2
usage/load failures.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_wf_check():
    spec = importlib.util.spec_from_file_location(
        "wf_check", os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "wf_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _synth_gen(record_spec: dict, n: int, seed: int = 0):
    """A zero-arg generator factory producing ``n`` records matching
    ``record_spec``: monotone values for ``id``/``ts``-style lanes,
    ints in [0, 32) for everything integral (safe under the targets'
    ``max_keys=64`` tables), [0, 1) floats.  Every value is a PURE
    function of the record index — no hidden RNG state, so a
    checkpointed target replays deterministically (WF611-clean)."""
    import numpy as np

    def gen():
        for i in range(n):
            # Knuth multiplicative hash of (index, lane) — scrambled
            # but replay-identical
            h = (i + seed) * 2654435761
            rec = {}
            for j, (name, proto) in enumerate(record_spec.items()):
                dt = np.asarray(proto).dtype
                v = (h ^ (j * 0x9E3779B9)) & 0xFFFFFFFF
                if name in ("id", "ts", "timestamp"):
                    rec[name] = dt.type(i)
                elif np.issubdtype(dt, np.integer):
                    rec[name] = dt.type(v % 32)
                elif np.issubdtype(dt, np.bool_):
                    rec[name] = dt.type(i & 1)
                else:
                    rec[name] = dt.type((v % 4096) / 4096.0)
            yield rec
    return gen


def _drive(graph, n: int) -> bool:
    """Substitute a seeded synthetic stream into every EMPTY source of
    ``graph`` (generators that already yield records keep their own
    stream — the chaos cells drive themselves) and run the graph to
    completion so its programs compile.  Returns True when it ran."""
    subbed = live = 0
    for mp in graph._all_pipes():
        for op in mp.operators:
            gen_fn = getattr(op, "gen_fn", None)
            spec = getattr(op, "record_spec", None)
            if gen_fn is None:
                continue
            if next(gen_fn(), None) is None and isinstance(spec, dict):
                op.gen_fn = _synth_gen(spec, n)
                subbed += 1
            else:
                live += 1
    if not (subbed or live):
        return False
    from windflow_tpu.analysis.diagnostics import PreflightError
    try:
        graph.run()
    except PreflightError as e:
        # the graph's own pre-flight (which folds this same dry-lower
        # audit) refused to start — the audit below reports the
        # findings; nothing compiled, so it takes the dry-lower path
        print(f"wf_ir: drive blocked by pre-flight: {e}", file=sys.stderr)
        return False
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("apps", nargs="+",
                    help="APP_MODULE or APP_MODULE:ATTR building the "
                         "PipeGraph (several allowed)")
    ap.add_argument("--drive", type=int, default=0, metavar="N",
                    help="feed N seeded synthetic records into empty "
                         "sources and run each graph before auditing "
                         "(0 = audit composed graphs only: captured "
                         "programs + kernel dry lower)")
    ap.add_argument("--json", action="store_true",
                    help="emit per-app reports as one JSON object")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on warnings too")
    args = ap.parse_args(argv)

    load_graph = _load_wf_check().load_graph
    from windflow_tpu.analysis import ir_audit

    if not ir_audit.ENABLED:
        print("wf_ir: FAIL: WF_TPU_IR_AUDIT=0 disables capture — "
              "nothing to audit", file=sys.stderr)
        return 2

    out = {}
    total_errors = total_findings = 0
    claimed = set()
    for app in args.apps:
        g = load_graph(app)
        if args.drive:
            _drive(g, args.drive)
        report = ir_audit.audit_graph(g)
        claimed |= report.op_names
        errors = [d for d in report.findings if d.severity == "error"]
        total_errors += len(errors)
        total_findings += len(report.findings)
        out[app] = {
            "graph": g.name,
            "errors": len(errors),
            "warnings": len(report.findings) - len(errors),
            **report.to_json(),
        }
        if not args.json:
            for d in report.findings:
                print(str(d))
            print(f"wf_ir: {app} ({g.name}): "
                  f"{len(errors)} error(s), "
                  f"{len(report.findings) - len(errors)} warning(s), "
                  f"{report.suppressed} suppressed, "
                  f"{report.programs_audited} program(s) "
                  f"({report.dry_lowered} dry-lowered, "
                  f"{len(report.pending)} pending) in "
                  f"{report.to_json()['check_ms']} ms")
    # orphan sweep: framework programs (staging pack/unpack, fused-away
    # flush paths) that no graph's wrappers claimed — audited
    # context-free so every program the process compiled is covered
    orphans = ir_audit.audit_orphans(claimed)
    if orphans.programs_audited:
        errors = [d for d in orphans.findings if d.severity == "error"]
        total_errors += len(errors)
        total_findings += len(orphans.findings)
        out["(framework programs)"] = {
            "errors": len(errors),
            "warnings": len(orphans.findings) - len(errors),
            **orphans.to_json(),
        }
        if not args.json:
            for d in orphans.findings:
                print(str(d))
            print(f"wf_ir: (framework programs): "
                  f"{len(errors)} error(s), "
                  f"{len(orphans.findings) - len(errors)} warning(s), "
                  f"{orphans.programs_audited} program(s)")
    if args.json:
        print(json.dumps(out, indent=2))
    if total_errors or (args.strict and total_findings):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
