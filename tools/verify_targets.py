#!/usr/bin/env python
"""Zero-arg graph factories for the wfverify CI stage.

``ci/run_tests.sh`` runs ``tools/wf_verify.py --strict`` over these
entrypoints — the bench e2e pipeline shape and one graph per chaos
family — so every kernel the repo itself ships stays clean under the
object-level verifier (``windflow_tpu/analysis/tracecheck.py``).  The
factories compose but never start their graphs: verification needs the
live callables, not a run.

The deliberately-violating determinism family (``wallclock``,
``durability/chaos.py``) is NOT listed here: it exists to be flagged
(WF612), which ``tests/test_tracecheck.py`` asserts — a strict CI pass
over it would always fail by design.
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_e2e():
    """The representative bench pipeline shape (bench.py ``_e2e_graph``):
    columnar source spec → MapTPU → chained FilterTPU → FFAT CB window →
    columnar sink."""
    import numpy as np

    import windflow_tpu as wf
    src = (wf.Source_Builder(lambda: iter(()))
           .withOutputBatchSize(4096)
           .withRecordSpec({"key": np.int32(0),
                            "v0": np.float32(0.0)}).build())
    m = wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "v0": t["v0"] * 1.5 + 1.0}).build()
    f = wf.FilterTPU_Builder(lambda t: (t["key"] & 7) != 7).build()
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v0"], lambda a, b: a + b)
         .withCBWindows(64, 16)
         .withKeyBy(lambda t: t["key"]).withMaxKeys(64).build())
    g = wf.PipeGraph("verify_bench_e2e")
    pipe = g.add_source(src)
    pipe.add(m)
    pipe.chain(f)
    pipe.add(w).add_sink(
        wf.Sink_Builder(lambda r: None).withColumnarSink(defer=4).build())
    return g


def _chaos(family: str):
    from windflow_tpu.durability.chaos import make_cell
    ckpt = tempfile.mkdtemp(prefix=f"wfverify_{family}_ck_")
    out = tempfile.mkdtemp(prefix=f"wfverify_{family}_out_") \
        if family in ("stateless_chain", "wallclock") else None
    cell = make_cell(family, ckpt, out_dir=out, n=64)
    return cell["factory"]()


def chaos_window_cb():
    return _chaos("window_cb")


def chaos_window_tb():
    return _chaos("window_tb")


def chaos_reduce():
    return _chaos("reduce")


def chaos_stateful():
    return _chaos("stateful")


def chaos_stateless_chain():
    return _chaos("stateless_chain")
