#!/usr/bin/env python
"""Zero-arg graph factories for the wfverify CI stage.

``ci/run_tests.sh`` runs ``tools/wf_verify.py --strict`` over these
entrypoints — the bench e2e pipeline shape and one graph per chaos
family — so every kernel the repo itself ships stays clean under the
object-level verifier (``windflow_tpu/analysis/tracecheck.py``).  The
factories compose but never start their graphs: verification needs the
live callables, not a run.

The deliberately-violating determinism family (``wallclock``,
``durability/chaos.py``) is NOT listed here: it exists to be flagged
(WF612), which ``tests/test_tracecheck.py`` asserts — a strict CI pass
over it would always fail by design.
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_e2e():
    """The representative bench pipeline shape (bench.py ``_e2e_graph``):
    columnar source spec → MapTPU → chained FilterTPU → FFAT CB window →
    columnar sink."""
    import numpy as np

    import windflow_tpu as wf
    src = (wf.Source_Builder(lambda: iter(()))
           .withOutputBatchSize(4096)
           .withRecordSpec({"key": np.int32(0),
                            "v0": np.float32(0.0)}).build())
    m = wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "v0": t["v0"] * 1.5 + 1.0}).build()
    f = wf.FilterTPU_Builder(lambda t: (t["key"] & 7) != 7).build()
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v0"], lambda a, b: a + b)
         .withCBWindows(64, 16)
         .withKeyBy(lambda t: t["key"]).withMaxKeys(64).build())
    g = wf.PipeGraph("verify_bench_e2e")
    pipe = g.add_source(src)
    pipe.add(m)
    pipe.chain(f)
    pipe.add(w).add_sink(
        wf.Sink_Builder(lambda r: None).withColumnarSink(defer=4).build())
    return g


def wire_ingest():
    """Compressed-ingest shape (windflow_tpu/wire.py): a declared-spec
    source staging wire-compressed batches — monotone ts/id lanes, a
    low-cardinality dict lane, a raw float lane — into a keyed reduce.
    Verifies the wire plane's decode-bearing graph composes clean under
    wfverify (the decode itself is framework code inside the unpack
    program; this pins the USER kernels around a compressed edge)."""
    import numpy as np

    import windflow_tpu as wf
    src = (wf.Source_Builder(lambda: iter(()))
           .withOutputBatchSize(4096)
           .withRecordSpec({"id": np.int64(0), "key": np.int32(0),
                            "v": np.float32(0.0)}).build())
    red = (wf.ReduceTPU_Builder(
        lambda a, b: {"id": jnp_max(a["id"], b["id"]),
                      "key": jnp_max(a["key"], b["key"]),
                      "v": jnp_max(a["v"], b["v"])})
        .withKeyBy(lambda t: t["key"]).withMaxKeys(64)
        .withMonoidCombiner("max").build())
    g = wf.PipeGraph("verify_wire_ingest")
    g.add_source(src).add(red).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    return g


def jnp_max(a, b):
    import jax.numpy as jnp
    return jnp.maximum(a, b)


def pallas_window():
    """Pallas-kernel-enabled window shape (windflow_tpu/kernels): a
    declared-monoid CB window + a declared-dense reduce with the
    kernels FORCED on — the grouping, pane-combine, and segmented-
    reduce kernel bodies all trace into the verified programs, so
    wfverify pins the kernel-bearing builds trace-safe/deterministic
    exactly like the lax ones."""
    import numpy as np

    import windflow_tpu as wf
    src = (wf.Source_Builder(lambda: iter(()))
           .withOutputBatchSize(4096)
           .withRecordSpec({"key": np.int32(0),
                            "v0": np.float32(0.0)}).build())
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v0"],
                                    lambda a, b: a + b)
         .withCBWindows(64, 16)
         .withKeyBy(lambda t: t["key"]).withMaxKeys(64)
         .withSumCombiner().build())
    # the reduce combines WINDOW OUTPUT records ({key, value, wid}) —
    # wf_ir --drive actually runs this graph, so the combiner must match
    # the upstream record structure, not the source spec
    red = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": jnp_max(a["key"], b["key"]),
                          "value": jnp_max(a["value"], b["value"]),
                          "wid": jnp_max(a["wid"], b["wid"])})
           .withKeyBy(lambda t: t["key"]).withMaxKeys(64)
           .withMonoidCombiner("max").build())
    g = wf.PipeGraph("verify_pallas_window",
                     config=wf.Config(pallas_kernels="1"))
    pipe = g.add_source(src)
    pipe.add(w)
    pipe.add(red)
    pipe.add_sink(wf.Sink_Builder(lambda r: None).build())
    return g


def megastep_latency():
    """Megastep + latency-ledger shape (windflow_tpu/megastep.py,
    monitoring/latency_ledger.py): K=4 staged sweeps folded into one
    compiled scan program feeding a CB window, with the per-batch
    latency ledger harvesting trace lanes — the two post-PR-10 hot
    paths (`MegastepEdge.offer`/`run`/drain, `LatencyLedger.harvest`)
    ride the verified/audited program set like every older plane."""
    import numpy as np

    import windflow_tpu as wf
    src = (wf.Source_Builder(lambda: iter(()))
           .withOutputBatchSize(4096)
           .withRecordSpec({"key": np.int32(0),
                            "v0": np.float32(0.0)}).build())
    m = wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "v0": t["v0"] * 0.5}).build()
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v0"],
                                    lambda a, b: a + b)
         .withCBWindows(64, 16)
         .withKeyBy(lambda t: t["key"]).withMaxKeys(64)
         .withSumCombiner().build())
    g = wf.PipeGraph("verify_megastep_latency",
                     config=wf.Config(megastep_sweeps=4,
                                      latency_ledger=True))
    pipe = g.add_source(src)
    pipe.add(m)
    pipe.add(w)
    pipe.add_sink(wf.Sink_Builder(lambda r: None).build())
    return g


def _chaos(family: str):
    from windflow_tpu.durability.chaos import make_cell
    ckpt = tempfile.mkdtemp(prefix=f"wfverify_{family}_ck_")
    out = tempfile.mkdtemp(prefix=f"wfverify_{family}_out_") \
        if family in ("stateless_chain", "wallclock") else None
    cell = make_cell(family, ckpt, out_dir=out, n=64)
    return cell["factory"]()


def chaos_window_cb():
    return _chaos("window_cb")


def chaos_window_tb():
    return _chaos("window_tb")


def chaos_reduce():
    return _chaos("reduce")


def chaos_stateful():
    return _chaos("stateful")


def chaos_stateless_chain():
    return _chaos("stateless_chain")
