#!/usr/bin/env python
"""wf_doctor: render a windflow_tpu postmortem bundle into a diagnosis.

A crash or watchdog-confirmed stall writes a black-box bundle
(``PipeGraph.dump_postmortem`` — flight-recorder rings, the last stats
report, health verdict timeline + stall attribution, jit/device tables,
the sweep ledger's per-hop dispatch/HBM attribution, preflight
findings).  This tool turns that directory into a human
diagnosis — or validates it — with **no jax installed** (pure stdlib,
same scrape-host stance as ``tools/wf_metrics.py``).

Usage::

    python tools/wf_doctor.py log/app_postmortem            # diagnose
    python tools/wf_doctor.py --check log/app_postmortem    # validate:
        # manifest schema, every listed file parses, health states and
        # span stages are legal, stall attribution names a known
        # operator; exit 1 on any violation
    python tools/wf_doctor.py --json log/app_postmortem     # machine-
        # readable diagnosis (the same fields the text render shows)

The CI round trip (tests/test_health.py) seeds a stall, lets the crash
path write a bundle, and runs ``--check`` on it in a subprocess.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

#: mirrors monitoring/health.py (kept literal: this file must not import
#: the package — the package __init__ imports jax)
SCHEMA = "wf-postmortem/1"
STATES = ("OK", "ROOFLINE_DEGRADED", "SLO_VIOLATED", "OVER_BUDGET",
          "BACKPRESSURED", "STALLED", "FAILED")
#: mirrors monitoring/calibration.py (SCHEMA + the provenance
#: vocabulary — calibrated tags carry an age suffix, e.g.
#: "calibrated(3h)")
CALIBRATION_SCHEMA = "wf-calibration/1"
PROVENANCE_FIXED = ("measured", "modeled", "interpret")


def _legal_provenance(tag) -> bool:
    return tag in PROVENANCE_FIXED or (
        isinstance(tag, str) and tag.startswith("calibrated("))
#: mirrors monitoring/latency_ledger.py SEGMENTS
LATENCY_SEGMENTS = ("staged_to_emitted", "emitted_to_dispatched",
                    "dispatched_to_device_done",
                    "device_done_to_collected", "collected_to_sunk")
STAGE_NAMES = ("staged", "emitted", "dispatched", "device_done",
               "collected", "sunk")
SECTIONS = ("stats.json", "events.json", "health.json", "device.json",
            "jit.json", "preflight.json")
#: sections newer writers add; validated when present, but their absence
#: must not reject a bundle written before they existed (same schema) —
#: this tool's job is exactly the historical crash bundle
OPTIONAL_SECTIONS = ("sweep.json", "durability.json", "shard.json",
                     "reshard.json", "latency.json", "ir_audit.json",
                     "tenant.json", "roofline.json", "calibration.json")
#: reshard executor timeline events (windflow_tpu/serving/executor.py)
RESHARD_EVENTS = ("triggered", "move_keys", "split_hot_key", "admission",
                  "recovered", "scale_down", "move_skipped")


class BundleError(Exception):
    pass


def load_bundle(path: str) -> dict:
    """Read manifest + every section it lists.  Raises
    :class:`BundleError` on structural violations (the --check half);
    sections recorded under manifest ``errors`` are allowed to be
    absent — a crash-path bundle degrades per section by design."""
    if not os.path.isdir(path):
        raise BundleError(f"{path} is not a bundle directory")
    mpath = os.path.join(path, "manifest.json")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except OSError as e:
        raise BundleError(f"no readable manifest.json: {e}") from None
    except ValueError as e:
        raise BundleError(f"manifest.json is not valid JSON: {e}") from None
    if manifest.get("schema") != SCHEMA:
        raise BundleError(f"unknown bundle schema "
                          f"{manifest.get('schema')!r} (want {SCHEMA!r})")
    for key in ("app", "reason", "written_at_usec", "files", "errors"):
        if key not in manifest:
            raise BundleError(f"manifest missing {key!r}")
    sections = {}
    for name in manifest["files"]:
        fp = os.path.join(path, name)
        try:
            with open(fp) as f:
                sections[name] = json.load(f)
        except OSError as e:
            raise BundleError(f"manifest lists {name} but it is "
                              f"unreadable: {e}") from None
        except ValueError as e:
            raise BundleError(f"{name} is not valid JSON: {e}") from None
    return {"dir": path, "manifest": manifest, "sections": sections}


def validate(bundle: dict) -> None:
    """The --check contract beyond load_bundle's structural pass."""
    manifest = bundle["manifest"]
    sections = bundle["sections"]
    missing = [s for s in SECTIONS
               if s not in sections and s not in manifest["errors"]]
    if missing:
        raise BundleError(
            f"sections neither written nor accounted for in "
            f"manifest errors: {missing}")
    health = sections.get("health.json") or {}
    verdicts = health.get("verdicts") or {}
    for op, v in verdicts.items():
        if v.get("state") not in STATES:
            raise BundleError(
                f"health.json: operator {op!r} has illegal state "
                f"{v.get('state')!r} (want one of {STATES})")
    for entry in health.get("timeline") or []:
        for op, state in (entry.get("changes") or {}).items():
            if state not in STATES:
                raise BundleError(
                    f"health.json timeline: illegal state {state!r} "
                    f"for {op!r}")
    stall = health.get("last_stall")
    if stall and stall.get("root_cause") is not None \
            and stall["root_cause"] not in verdicts:
        raise BundleError(
            f"last_stall attributes {stall['root_cause']!r} but that "
            "operator has no verdict entry")
    for e in sections.get("events.json") or []:
        if e.get("stage") not in STAGE_NAMES:
            raise BundleError(
                f"events.json: illegal span stage {e.get('stage')!r}")
    sweep = sections.get("sweep.json") or {}
    if sweep.get("enabled"):
        for op, hop in (sweep.get("per_hop") or {}).items():
            if not isinstance(hop, dict):
                raise BundleError(
                    f"sweep.json: hop {op!r} is not an object")
            for key in ("dispatches", "batches"):
                v = hop.get(key)
                if v is not None and not isinstance(v, int):
                    raise BundleError(
                        f"sweep.json: hop {op!r} field {key!r} must be "
                        f"an integer, got {v!r}")
            bpt = hop.get("bytes_per_tuple")
            if bpt is not None and (not isinstance(bpt, (int, float))
                                    or bpt < 0):
                raise BundleError(
                    f"sweep.json: hop {op!r} bytes_per_tuple {bpt!r} is "
                    "not a non-negative number")
    shard = sections.get("shard.json") or {}
    if shard.get("enabled") and "error" not in shard:
        per_op = shard.get("per_op")
        if not isinstance(per_op, dict):
            raise BundleError("shard.json: per_op must be an object")
        for op, entry in per_op.items():
            if not isinstance(entry, dict):
                raise BundleError(
                    f"shard.json: operator {op!r} entry is not an object")
            reps = entry.get("replicas")
            if reps is not None and not isinstance(reps, list):
                raise BundleError(
                    f"shard.json: operator {op!r} replicas must be a "
                    "list")
            for r in reps or []:
                if not isinstance(r, dict):
                    raise BundleError(
                        f"shard.json: operator {op!r} replica entry "
                        f"{r!r} is not an object")
                q = r.get("queue_depth")
                if not isinstance(q, int) or q < 0:
                    raise BundleError(
                        f"shard.json: operator {op!r} shard queue_depth "
                        f"{q!r} is not a non-negative integer")
            load = entry.get("load")
            if load is not None:
                if not isinstance(load, dict):
                    raise BundleError(
                        f"shard.json: operator {op!r} load is not an "
                        "object")
                ratio = load.get("imbalance_ratio")
                if ratio is not None and (
                        not isinstance(ratio, (int, float)) or ratio < 0):
                    raise BundleError(
                        f"shard.json: operator {op!r} imbalance_ratio "
                        f"{ratio!r} is not a non-negative number")
                hks = load.get("hot_keys")
                if hks is not None and not isinstance(hks, list):
                    raise BundleError(
                        f"shard.json: operator {op!r} hot_keys must be "
                        "a list")
                for hk in hks or []:
                    if not isinstance(hk, dict):
                        raise BundleError(
                            f"shard.json: operator {op!r} hot-key entry "
                            f"{hk!r} is not an object")
                    v = hk.get("est_tuples")
                    if not isinstance(v, int) or v < 0:
                        raise BundleError(
                            f"shard.json: operator {op!r} hot-key "
                            f"est_tuples {v!r} is not a non-negative "
                            "integer")
    dur = sections.get("durability.json") or {}
    if dur.get("enabled") and "error" not in dur:
        for key in ("epochs_committed", "dedupe_hits", "sink_commits"):
            v = dur.get(key)
            if not isinstance(v, int) or v < 0:
                raise BundleError(
                    f"durability.json: {key!r} must be a non-negative "
                    f"integer, got {v!r}")
        for key in ("last_checkpoint_ms", "restore_ms"):
            v = dur.get(key)
            if v is not None and (not isinstance(v, (int, float))
                                  or v < 0):
                raise BundleError(
                    f"durability.json: {key!r} must be a non-negative "
                    f"number or null, got {v!r}")
        ep = dur.get("restored_epoch")
        if ep is not None and not isinstance(ep, int):
            raise BundleError(
                f"durability.json: restored_epoch must be an integer "
                f"or null, got {ep!r}")
    rsh = sections.get("reshard.json") or {}
    if rsh.get("enabled") and "error" not in rsh:
        for key in ("plans_applied", "keys_moved", "splits_applied",
                    "admission_throttles", "preagg_folds"):
            v = rsh.get(key)
            if not isinstance(v, int) or v < 0:
                raise BundleError(
                    f"reshard.json: {key!r} must be a non-negative "
                    f"integer, got {v!r}")
        af = rsh.get("admission_factor")
        if not isinstance(af, (int, float)) or not 0 < af <= 1:
            raise BundleError(
                f"reshard.json: admission_factor must be in (0, 1], "
                f"got {af!r}")
        tl = rsh.get("timeline")
        if not isinstance(tl, list):
            raise BundleError("reshard.json: timeline must be a list")
        for e in tl:
            if not isinstance(e, dict) \
                    or e.get("event") not in RESHARD_EVENTS:
                raise BundleError(
                    f"reshard.json: illegal timeline entry {e!r}")
    ira = sections.get("ir_audit.json") or {}
    if ira.get("enabled") and "error" not in ira:
        for key in ("programs_audited", "dry_lowered", "suppressed"):
            v = ira.get(key)
            if not isinstance(v, int) or v < 0:
                raise BundleError(
                    f"ir_audit.json: {key!r} must be a non-negative "
                    f"integer, got {v!r}")
        for key in ("findings", "pending"):
            if not isinstance(ira.get(key), list):
                raise BundleError(
                    f"ir_audit.json: {key!r} must be a list")
        for f in ira["findings"]:
            if not isinstance(f, dict) \
                    or not str(f.get("code", "")).startswith("WF9"):
                raise BundleError(
                    f"ir_audit.json: finding {f!r} is not an object "
                    "with a WF9xx code")
    latp = sections.get("latency.json") or {}
    if latp.get("enabled") and "error" not in latp:
        for key in ("traces_decomposed", "traces_dropped", "events_lost"):
            v = latp.get(key)
            if not isinstance(v, int) or v < 0:
                raise BundleError(
                    f"latency.json: {key!r} must be a non-negative "
                    f"integer, got {v!r}")
        segs = latp.get("segments_total_usec")
        if not isinstance(segs, dict):
            raise BundleError(
                "latency.json: segments_total_usec must be an object")
        for seg, v in segs.items():
            if seg not in LATENCY_SEGMENTS:
                raise BundleError(
                    f"latency.json: unknown segment {seg!r} "
                    f"(want one of {LATENCY_SEGMENTS})")
            if not isinstance(v, (int, float)) or v < 0:
                raise BundleError(
                    f"latency.json: segment {seg!r} total {v!r} is not "
                    "a non-negative number")
        per_op = latp.get("per_op")
        if not isinstance(per_op, dict):
            raise BundleError("latency.json: per_op must be an object")
        for op, entry in per_op.items():
            if not isinstance(entry, dict):
                raise BundleError(
                    f"latency.json: operator {op!r} entry is not an "
                    "object")
            share = entry.get("budget_share")
            if not isinstance(share, (int, float)) or not 0 <= share <= 1:
                raise BundleError(
                    f"latency.json: operator {op!r} budget_share "
                    f"{share!r} is not in [0, 1]")
            dom = entry.get("dominant_segment")
            if dom is not None and dom not in LATENCY_SEGMENTS:
                raise BundleError(
                    f"latency.json: operator {op!r} dominant_segment "
                    f"{dom!r} is not a known segment")
            for seg in entry.get("segments_usec") or {}:
                if seg not in LATENCY_SEGMENTS:
                    raise BundleError(
                        f"latency.json: operator {op!r} histogram "
                        f"segment {seg!r} is not a known segment")
        slo = latp.get("slo") or {}
        verdict = slo.get("verdict")
        if verdict is not None:
            if not isinstance(verdict, dict) \
                    or verdict.get("state") != "SLO_VIOLATED":
                raise BundleError(
                    f"latency.json: slo.verdict {verdict!r} must be an "
                    "object with state SLO_VIOLATED")
            if verdict.get("dominant_op") is not None \
                    and verdict["dominant_op"] not in per_op:
                raise BundleError(
                    f"latency.json: slo.verdict attributes "
                    f"{verdict['dominant_op']!r} but that operator has "
                    "no per_op entry")
    ten = sections.get("tenant.json") or {}
    if ten.get("enabled") and "error" not in ten:
        tenants = ten.get("tenants")
        if not isinstance(tenants, dict):
            raise BundleError("tenant.json: tenants must be an object")
        for tname, agg in tenants.items():
            if not isinstance(agg, dict):
                raise BundleError(
                    f"tenant.json: tenant {tname!r} entry is not an "
                    "object")
            for key in ("dispatches", "h2d_bytes", "d2h_bytes",
                        "resident_state_bytes"):
                v = agg.get(key)
                if v is not None and (not isinstance(v, int) or v < 0):
                    raise BundleError(
                        f"tenant.json: tenant {tname!r} field {key!r} "
                        f"must be a non-negative integer, got {v!r}")
            budget = agg.get("budget")
            if budget is not None:
                if not isinstance(budget, dict):
                    raise BundleError(
                        f"tenant.json: tenant {tname!r} budget is not "
                        "an object")
                pressure = budget.get("pressure")
                if pressure is not None and (
                        not isinstance(pressure, (int, float))
                        or pressure < 0):
                    raise BundleError(
                        f"tenant.json: tenant {tname!r} budget pressure "
                        f"{pressure!r} is not a non-negative number")
                v = budget.get("verdict")
                if v is not None:
                    if not isinstance(v, dict) \
                            or v.get("state") != "OVER_BUDGET":
                        raise BundleError(
                            f"tenant.json: tenant {tname!r} verdict "
                            f"{v!r} must be an object with state "
                            "OVER_BUDGET")
                    if v.get("heaviest_op") is not None \
                            and v["heaviest_op"] \
                            not in (agg.get("per_op") or {}):
                        raise BundleError(
                            f"tenant.json: tenant {tname!r} verdict "
                            f"attributes {v['heaviest_op']!r} but that "
                            "operator has no per_op entry")
        attributed = ten.get("attributed")
        if attributed is not None:
            if not isinstance(attributed, dict):
                raise BundleError(
                    "tenant.json: attributed must be an object")
            frac = attributed.get("staged_fraction")
            if frac is not None and (not isinstance(frac, (int, float))
                                     or frac < 0):
                raise BundleError(
                    f"tenant.json: attributed staged_fraction {frac!r} "
                    "is not a non-negative number")
    calib = sections.get("calibration.json") or {}
    if calib and "error" not in calib:
        if calib.get("schema") != CALIBRATION_SCHEMA:
            raise BundleError(
                f"calibration.json: schema {calib.get('schema')!r} "
                f"(want {CALIBRATION_SCHEMA!r})")
        consts = calib.get("constants")
        if not isinstance(consts, dict):
            raise BundleError(
                "calibration.json: constants must be an object")
        for key, slot in consts.items():
            if not isinstance(slot, dict):
                raise BundleError(
                    f"calibration.json: constant {key!r} entry is not "
                    "an object")
            v = slot.get("value")
            if not isinstance(v, (int, float)) or v < 0:
                raise BundleError(
                    f"calibration.json: constant {key!r} value {v!r} is "
                    "not a non-negative number")
            if not _legal_provenance(slot.get("provenance")):
                raise BundleError(
                    f"calibration.json: constant {key!r} provenance "
                    f"{slot.get('provenance')!r} is not in the "
                    "measured/modeled/calibrated(age)/interpret "
                    "vocabulary")
    rfl = sections.get("roofline.json") or {}
    if rfl.get("enabled") and "error" not in rfl:
        per_hop = rfl.get("per_hop")
        if not isinstance(per_hop, dict):
            raise BundleError("roofline.json: per_hop must be an object")
        for op, hop in per_hop.items():
            if not isinstance(hop, dict):
                raise BundleError(
                    f"roofline.json: hop {op!r} entry is not an object")
            for key in ("achieved_tuples_per_sec", "bytes_per_tuple",
                        "ratio_vs_roofline"):
                v = hop.get(key)
                if v is not None and (not isinstance(v, (int, float))
                                      or v < 0):
                    raise BundleError(
                        f"roofline.json: hop {op!r} field {key!r} "
                        f"{v!r} is not a non-negative number")
            prov = hop.get("bytes_per_tuple_provenance")
            if prov is not None and not _legal_provenance(prov):
                raise BundleError(
                    f"roofline.json: hop {op!r} bytes provenance "
                    f"{prov!r} is not a legal tag")
        if not _legal_provenance(rfl.get("bandwidth_provenance")):
            raise BundleError(
                f"roofline.json: bandwidth_provenance "
                f"{rfl.get('bandwidth_provenance')!r} is not a legal "
                "tag")
        v = rfl.get("verdict")
        if v is not None:
            if not isinstance(v, dict) \
                    or v.get("state") != "ROOFLINE_DEGRADED":
                raise BundleError(
                    f"roofline.json: verdict {v!r} must be an object "
                    "with state ROOFLINE_DEGRADED")
            if v.get("dominant_op") is not None \
                    and v["dominant_op"] not in per_hop:
                raise BundleError(
                    f"roofline.json: verdict attributes "
                    f"{v['dominant_op']!r} but that hop has no per_hop "
                    "entry")


def diagnose(bundle: dict) -> dict:
    """Condense the bundle into the fields a responder reads first."""
    manifest = bundle["manifest"]
    sections = bundle["sections"]
    health = sections.get("health.json") or {}
    verdicts = health.get("verdicts") or {}
    stats = sections.get("stats.json") or {}
    gauges = stats.get("Gauges") or {}
    jit = (sections.get("jit.json") or {}).get("totals") or {}
    stall = health.get("last_stall") or None
    bad = {op: v for op, v in verdicts.items() if v.get("state") != "OK"}
    sweep = sections.get("sweep.json") or {}
    hops = sweep.get("per_hop") or {}
    top_hop = None
    if hops:
        ranked = sorted(hops.items(),
                        key=lambda kv: kv[1].get("bytes_per_tuple") or 0,
                        reverse=True)
        name, h = ranked[0]
        top_hop = {"op": name,
                   "bytes_per_tuple": h.get("bytes_per_tuple"),
                   "dispatches_per_batch": h.get("dispatches_per_batch"),
                   "excess_vs_model": h.get("excess_vs_model")}
    donation_misses = {op: h["donation_miss"] for op, h in hops.items()
                       if h.get("donation_miss")}
    shard = sections.get("shard.json") or {}
    shard_imbalance = None
    if shard.get("enabled") and "error" not in shard:
        tot = shard.get("totals") or {}
        if tot.get("max_imbalance_op"):
            worst = (shard.get("per_op") or {}) \
                .get(tot["max_imbalance_op"]) or {}
            load = worst.get("load") or {}
            hot = (load.get("hot_keys") or [{}])[0]
            shard_imbalance = {
                "op": tot["max_imbalance_op"],
                "imbalance_ratio": tot.get("max_imbalance_ratio"),
                "hot_shard": load.get("hot_shard"),
                "hot_key": hot.get("key"),
                "hot_key_share": tot.get("hot_key_share"),
                "loads": load.get("tuples"),
                "ici_bytes_per_tuple": tot.get("ici_bytes_per_tuple"),
            }
    dur = sections.get("durability.json") or {}
    durability = None
    if dur.get("enabled") and "error" not in dur:
        durability = {
            "epochs_committed": dur.get("epochs_committed"),
            "last_checkpoint_ms": dur.get("last_checkpoint_ms"),
            "checkpoint_bytes_total": dur.get("checkpoint_bytes_total"),
            "restored_epoch": dur.get("restored_epoch"),
            "dedupe_hits": dur.get("dedupe_hits"),
            "dir": dur.get("dir"),
        }
    latp = sections.get("latency.json") or {}
    latency = None
    if latp.get("enabled") and "error" not in latp:
        ranked = sorted((latp.get("per_op") or {}).items(),
                        key=lambda kv: kv[1].get("budget_share") or 0,
                        reverse=True)
        top = None
        if ranked:
            name, entry = ranked[0]
            top = {"op": name,
                   "budget_share": entry.get("budget_share"),
                   "dominant_segment": entry.get("dominant_segment"),
                   "megastep_k": entry.get("megastep_k"),
                   "freshness_floor_usec":
                       entry.get("freshness_floor_usec")}
        slo = latp.get("slo") or {}
        latency = {
            "traces_decomposed": latp.get("traces_decomposed"),
            "traces_dropped": latp.get("traces_dropped"),
            "events_lost": latp.get("events_lost"),
            "e2e_p99_usec": (latp.get("e2e_usec") or {}).get("p99"),
            "top_op": top,
            "slo_budget_ms": slo.get("budget_ms"),
            "slo_active": slo.get("active"),
            "slo_verdict": slo.get("verdict") or slo.get("last_verdict"),
        }
    irap = sections.get("ir_audit.json") or {}
    ir_audit = None
    if irap.get("enabled") and "error" not in irap:
        ir_audit = {
            "programs_audited": irap.get("programs_audited"),
            "findings": irap.get("findings") or [],
            "suppressed": irap.get("suppressed"),
            "pending": irap.get("pending") or [],
        }
    tenp = sections.get("tenant.json") or {}
    tenancy = None
    if tenp.get("enabled") and "error" not in tenp:
        worst = None
        for tname, agg in (tenp.get("tenants") or {}).items():
            if not isinstance(agg, dict):
                continue
            budget = agg.get("budget") or {}
            row = {
                "tenant": tname,
                "graphs": agg.get("graphs") or [],
                "resident_state_bytes":
                    agg.get("resident_state_bytes"),
                "budget_bytes": budget.get("budget_bytes"),
                "pressure": budget.get("pressure"),
                "over_budget": bool(budget.get("active")),
                "heaviest_op": agg.get("heaviest_op"),
                "verdict": budget.get("verdict")
                    or budget.get("last_verdict"),
            }
            if worst is None or (row["pressure"] or -1.0) \
                    > (worst["pressure"] or -1.0):
                worst = row
        tenancy = {
            "tenants_total": len(tenp.get("tenants") or {}),
            "worst": worst,
            "attributed": tenp.get("attributed") or {},
        }
    calp = sections.get("calibration.json") or {}
    calibration = None
    if calp and "error" not in calp:
        consts = calp.get("constants") or {}
        calibration = {
            "enabled": bool(calp.get("enabled")),
            "source": calp.get("source"),
            "device_kind": calp.get("device_kind"),
            "calibrated_constants": sorted(
                k for k, s in consts.items()
                if isinstance(s, dict)
                and str(s.get("provenance", "")).startswith("calibrated(")),
            "modeled_constants": sorted(
                k for k, s in consts.items()
                if isinstance(s, dict)
                and s.get("provenance") == "modeled"),
        }
    rflp = sections.get("roofline.json") or {}
    roofline = None
    if rflp.get("enabled") and "error" not in rflp:
        worst_hop = None
        for op, hop in (rflp.get("per_hop") or {}).items():
            if not isinstance(hop, dict):
                continue
            ratio = hop.get("ratio_vs_roofline")
            if ratio is None:
                continue
            if worst_hop is None or ratio < worst_hop["ratio"]:
                worst_hop = {"op": op, "ratio": ratio,
                             "achieved_tuples_per_sec":
                                 hop.get("achieved_tuples_per_sec")}
        roofline = {
            "hops": len(rflp.get("per_hop") or {}),
            "dominant_op": rflp.get("dominant_op"),
            "bandwidth_provenance": rflp.get("bandwidth_provenance"),
            "worst_hop": worst_hop,
            "verdict": rflp.get("verdict") or rflp.get("last_verdict"),
        }
    rsh = sections.get("reshard.json") or {}
    reshard = None
    if rsh.get("enabled") and "error" not in rsh:
        reshard = {
            "plans_applied": rsh.get("plans_applied"),
            "keys_moved": rsh.get("keys_moved"),
            "splits_applied": rsh.get("splits_applied"),
            "preagg_folds": rsh.get("preagg_folds"),
            "admission_factor": rsh.get("admission_factor"),
            "quiesce_ms": rsh.get("quiesce_ms"),
            "recovery_ms": rsh.get("recovery_ms"),
            "ops": rsh.get("ops") or {},
            "timeline": rsh.get("timeline") or [],
        }
    return {
        "app": manifest.get("app"),
        "reason": manifest.get("reason"),
        "durability": durability,
        "latency": latency,
        "ir_audit": ir_audit,
        "tenancy": tenancy,
        "calibration": calibration,
        "roofline": roofline,
        "reshard": reshard,
        "written_at_usec": manifest.get("written_at_usec"),
        "graph_state": health.get("graph_state"),
        "stall_events": health.get("stall_events", 0),
        "root_cause": stall.get("root_cause") if stall else None,
        "unhealthy_operators": bad,
        "verdicts": verdicts,
        "timeline": health.get("timeline") or [],
        "throughput_1s_tps": gauges.get("throughput_1s_tps"),
        "dropped_tuples": stats.get("Dropped_tuples"),
        "recompiles": jit.get("recompiles"),
        "compile_ms_total": jit.get("compile_ms_total"),
        "span_events": len(sections.get("events.json") or []),
        "shard_imbalance": shard_imbalance,
        "sweep_top_hop": top_hop,
        "sweep_totals": sweep.get("totals") or None,
        "donation_misses": donation_misses,
        "section_errors": manifest.get("errors") or {},
    }


def _age(usec) -> str:
    return "?" if usec is None else f"{usec / 1e6:.1f}s"


def render_text(d: dict) -> str:
    lines = [
        f"wf_doctor: app '{d['app']}' — {d['reason']}",
        f"  graph state: {d['graph_state'] or '?'}   "
        f"stall events: {d['stall_events']}   "
        f"span events retained: {d['span_events']}",
    ]
    if d["root_cause"]:
        v = d["verdicts"].get(d["root_cause"], {})
        lines.append(
            f"  ROOT CAUSE: '{d['root_cause']}' stopped draining — "
            f"queue={v.get('queue_depth')}, "
            f"frontier={v.get('watermark_frontier_usec')}, "
            f"last advance {_age(v.get('last_advance_age_usec'))} ago")
    lines.append("  operators:")
    for op, v in d["verdicts"].items():
        extra = " [compile storm]" if v.get("compile_storm") else ""
        fail = f" — {v['failure']}" if v.get("failure") else ""
        lines.append(
            f"    {op:<24} {v.get('state', '?'):<14} "
            f"queue={v.get('queue_depth', 0):<6} "
            f"advance_age={_age(v.get('last_advance_age_usec'))}"
            f"{extra}{fail}")
    if d["timeline"]:
        lines.append("  verdict timeline (state changes):")
        for entry in d["timeline"][-12:]:
            changes = ", ".join(f"{op}→{s}" for op, s
                                in (entry.get("changes") or {}).items())
            lines.append(f"    t={entry.get('t_usec')}: {changes}")
    lines.append(
        f"  telemetry: throughput_1s={d['throughput_1s_tps']} tps, "
        f"dropped={d['dropped_tuples']}, "
        f"recompiles={d['recompiles']}, "
        f"compile_ms_total={d['compile_ms_total']}")
    if d.get("sweep_top_hop"):
        t = d["sweep_top_hop"]
        tot = d.get("sweep_totals") or {}
        n = lambda v: "?" if v is None else v  # cost tables may be absent
        lines.append(
            f"  sweep: hottest hop '{t['op']}' at "
            f"{n(t['bytes_per_tuple'])} B/tuple "
            f"({n(t['dispatches_per_batch'])} dispatch(es)/batch, "
            f"{n(t['excess_vs_model'])}x the record model); "
            f"graph total {n(tot.get('bytes_per_tuple'))} B/tuple over "
            f"{n(tot.get('dispatches_per_batch'))} dispatches/batch")
    if d.get("shard_imbalance"):
        s = d["shard_imbalance"]
        n = lambda v: "?" if v is None else v
        lines.append(
            f"  shard: worst imbalance '{s['op']}' at "
            f"{n(s['imbalance_ratio'])}x (hot shard {n(s['hot_shard'])}, "
            f"loads {n(s['loads'])}); hottest key {n(s['hot_key'])} "
            f"carries {n(s['hot_key_share'])} of the stream"
            + (f"; ICI {s['ici_bytes_per_tuple']} B/tuple"
               if s.get("ici_bytes_per_tuple") else ""))
    for op, miss in (d.get("donation_misses") or {}).items():
        lines.append(
            f"  donation miss: '{op}' re-copies "
            f"{miss.get('bytes_per_batch')} B/batch "
            f"({miss.get('candidate_leaves')} donatable leaf/leaves "
            "not donated)")
    if d.get("durability"):
        du = d["durability"]
        if not du.get("epochs_committed") \
                and du.get("restored_epoch") is None:
            # a crash before the first barrier leaves nothing to rebuild
            # from — saying "restartable" here would misdirect the
            # responder straight into restore()'s no-complete-epoch
            # error.  A restored graph that re-crashed before its first
            # NEW commit also reports epochs_committed 0, but its
            # restored_epoch proves the store holds complete epochs —
            # that case takes the restartable branch below.
            lines.append(
                "  durability: enabled but NO complete epoch committed "
                f"to {du['dir']!r} yet — PipeGraph.restore() has nothing "
                "to rebuild from; restart the app cold")
        else:
            lines.append(
                f"  durability: {du['epochs_committed']} epoch(s) "
                f"committed to {du['dir']!r} (last checkpoint "
                f"{du['last_checkpoint_ms']} ms, "
                f"{du['checkpoint_bytes_total']} snapshot bytes total); "
                + (f"restored from epoch {du['restored_epoch']}, "
                   f"{du['dedupe_hits']} replayed sink message(s) deduped "
                   "— restart the app with PipeGraph.restore() on this "
                   "store"
                   if du["restored_epoch"] is not None else
                   "restartable with PipeGraph.restore() on this store"))
    if d.get("latency"):
        la = d["latency"]
        n = lambda v: "?" if v is None else v
        lines.append(
            f"  latency: {n(la['traces_decomposed'])} trace(s) "
            f"decomposed (dropped={n(la['traces_dropped'])}, "
            f"ring events lost={n(la['events_lost'])}), "
            f"e2e p99 {n(la['e2e_p99_usec'])} µs")
        if la.get("top_op"):
            t = la["top_op"]
            share = t.get("budget_share")
            lines.append(
                f"    hottest op '{t['op']}' carries "
                f"{'?' if share is None else f'{share:.0%}'} of the "
                f"critical path, dominated by {n(t['dominant_segment'])}"
                + (f" (megastep K={t['megastep_k']}, freshness floor "
                   f"{n(t['freshness_floor_usec'])} µs)"
                   if t.get("megastep_k") else ""))
        if la.get("slo_budget_ms"):
            v = la.get("slo_verdict") or {}
            lines.append(
                f"    SLO budget {la['slo_budget_ms']} ms — "
                + ("VIOLATED: " + v.get("message", "?")
                   if la.get("slo_active")
                   else "within budget"
                   + (f" (last violation: {v.get('message')})"
                      if v else "")))
    if d.get("ir_audit"):
        ia = d["ir_audit"]
        finds = ia["findings"]
        lines.append(
            f"  IR audit: {ia['programs_audited']} lowered program(s) "
            f"audited — {len(finds)} WF9xx finding(s)"
            + (f", {ia['suppressed']} suppressed" if ia.get("suppressed")
               else "")
            + (f", pending (never lowered): {ia['pending']}"
               if ia.get("pending") else ""))
        for f in finds[:8]:
            lines.append(
                f"    {f.get('code')} [{f.get('severity')}] "
                f"'{f.get('node')}': {f.get('message')}")
    if d.get("tenancy"):
        tn = d["tenancy"]
        frac = (tn.get("attributed") or {}).get("staged_fraction")
        lines.append(
            f"  tenancy: {tn['tenants_total']} tenant(s) in process"
            + (f", attribution {frac:.0%} of staged bytes"
               if isinstance(frac, (int, float)) else ""))
        w = tn.get("worst")
        if w:
            n = lambda v: "?" if v is None else v
            press = w.get("pressure")
            lines.append(
                f"    worst pressure: '{w['tenant']}' at "
                f"{'?' if press is None else f'{press:.2f}x'} "
                f"({n(w['resident_state_bytes'])} B resident"
                + (f" / {w['budget_bytes']} B budget"
                   if w.get("budget_bytes") else "")
                + (f", heaviest op {w['heaviest_op']}"
                   if w.get("heaviest_op") else "") + ")")
            v = w.get("verdict")
            if v:
                tag = "OVER BUDGET (latched)" if w["over_budget"] \
                    else "last verdict"
                lines.append(f"    {tag}: {v.get('message')}")
    if d.get("calibration"):
        c = d["calibration"]
        cal = c.get("calibrated_constants") or []
        mod = c.get("modeled_constants") or []
        lines.append(
            "  calibration: "
            + (f"store '{c['source']}' for {c.get('device_kind') or '?'}"
               if c.get("enabled") else "no store loaded")
            + f" — {len(cal)} calibrated / {len(mod)} modeled constant(s)")
        if cal:
            lines.append(f"    calibrated: {', '.join(cal)}")
    if d.get("roofline"):
        r = d["roofline"]
        lines.append(
            f"  roofline: {r['hops']} fused hop(s) tracked "
            f"(bandwidth {r.get('bandwidth_provenance') or '?'})"
            + (f", dominant op '{r['dominant_op']}'"
               if r.get("dominant_op") else ""))
        w = r.get("worst_hop")
        if w and isinstance(w.get("ratio"), (int, float)):
            lines.append(
                f"    lowest ratio vs roofline: '{w['op']}' at "
                f"{w['ratio']:.3f}")
        v = r.get("verdict")
        if v:
            lines.append(
                f"    ROOFLINE DEGRADED: '{v.get('dominant_op')}' at "
                f"{v.get('ratio_vs_baseline')}x of trailing baseline")
    if d.get("reshard"):
        r = d["reshard"]
        lines.append(
            f"  Reshard executor: {r['plans_applied']} plan(s) applied "
            f"({r['keys_moved']} key(s) moved, {r['splits_applied']} "
            f"split(s), {r['preagg_folds']} tuple(s) pre-aggregated), "
            f"admission factor {r['admission_factor']}"
            + (f", last quiesce {r['quiesce_ms']} ms" if r.get(
                "quiesce_ms") is not None else "")
            + (f", recovery {r['recovery_ms']} ms" if r.get(
                "recovery_ms") is not None else ""))
        if r["timeline"]:
            lines.append("  reshard timeline:")
            for e in r["timeline"][-10:]:
                lines.append(
                    f"    t={e.get('t_usec')}: {e.get('op')} "
                    f"{e.get('event')} — {e.get('detail')}")
    if d["section_errors"]:
        lines.append(f"  degraded sections: {d['section_errors']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundle", help="postmortem bundle directory "
                                   "(PipeGraph.dump_postmortem output)")
    ap.add_argument("--check", action="store_true",
                    help="validate the bundle instead of rendering it")
    ap.add_argument("--json", action="store_true",
                    help="emit the diagnosis as JSON")
    args = ap.parse_args(argv)
    try:
        bundle = load_bundle(args.bundle)
        validate(bundle)
    except BundleError as e:
        print(f"wf_doctor: FAIL: {e}", file=sys.stderr)
        return 1
    if args.check:
        m = bundle["manifest"]
        print(f"wf_doctor: OK ({len(bundle['sections'])} sections, "
              f"app '{m['app']}', reason {m['reason']!r}"
              + (f", {len(m['errors'])} degraded" if m["errors"] else "")
              + ")")
        return 0
    d = diagnose(bundle)
    if args.json:
        json.dump(d, sys.stdout, indent=1)
        print()
    else:
        print(render_text(d))
    return 0


if __name__ == "__main__":
    sys.exit(main())
