#!/usr/bin/env python
"""wf_check: run the pre-flight graph checker against an application.

CLI face of ``PipeGraph.check()`` (windflow_tpu/analysis/preflight.py),
mirroring the ``tools/trace_export.py --check`` pattern: point it at the
module that builds your PipeGraph and get the FULL diagnostic list —
dtype/shape chain mismatches, window-spec errors, mesh divisibility,
watermark-mode conflicts — with zero device work and without running the
stream.

Usage::

    python tools/wf_check.py APP_MODULE            # e.g. myapp.pipeline
    python tools/wf_check.py APP_MODULE:ATTR       # a PipeGraph attribute
                                                   # or zero-arg factory
    python tools/wf_check.py ... --json            # machine-readable
    python tools/wf_check.py ... --strict          # exit 1 on warnings too

Without ``:ATTR`` the module is scanned for PipeGraph instances and
zero-arg callables named ``make_graph``/``build_graph``/``graph``.  Exit
status: 0 clean, 1 error-severity diagnostics found (or any diagnostic
under ``--strict``), 2 usage/load failures.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: module-level names probed (in order) when no :ATTR is given
FACTORY_NAMES = ("make_graph", "build_graph", "graph", "make_app", "app")


def fail(msg: str) -> None:
    print(f"wf_check: FAIL: {msg}", file=sys.stderr)
    sys.exit(2)


def _as_graph(obj):
    """A PipeGraph from an attribute: the instance itself, or the result
    of calling a zero-arg factory."""
    from windflow_tpu.graph.pipegraph import PipeGraph
    if isinstance(obj, PipeGraph):
        return obj
    if callable(obj):
        out = obj()
        if isinstance(out, PipeGraph):
            return out
    return None


def load_graph(spec: str):
    """``module`` or ``module:attr`` -> a composed (unstarted) PipeGraph."""
    mod_name, _, attr = spec.partition(":")
    try:
        mod = importlib.import_module(mod_name)
    except ImportError as e:
        fail(f"cannot import '{mod_name}': {e}")
    if attr:
        if not hasattr(mod, attr):
            fail(f"module '{mod_name}' has no attribute '{attr}'")
        g = _as_graph(getattr(mod, attr))
        if g is None:
            fail(f"'{mod_name}:{attr}' is neither a PipeGraph nor a "
                 "zero-arg factory returning one")
        return g
    from windflow_tpu.graph.pipegraph import PipeGraph
    for name in FACTORY_NAMES:
        if hasattr(mod, name):
            g = _as_graph(getattr(mod, name))
            if g is not None:
                return g
    for name in dir(mod):
        if isinstance(getattr(mod, name), PipeGraph):
            return getattr(mod, name)
    fail(f"no PipeGraph found in '{mod_name}' — expose one (or a factory "
         f"named one of {FACTORY_NAMES}), or pass 'module:attr'")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("app", help="APP_MODULE or APP_MODULE:ATTR building "
                                "the PipeGraph")
    ap.add_argument("--json", action="store_true",
                    help="emit diagnostics as a JSON array")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on warnings too")
    args = ap.parse_args(argv)

    g = load_graph(args.app)
    diags = g.check()
    errors = [d for d in diags if d.severity == "error"]
    if args.json:
        print(json.dumps({
            "app": args.app,
            "graph": g.name,
            "check_ms": g._preflight_ms,
            "errors": len(errors),
            "warnings": len(diags) - len(errors),
            "diagnostics": [d.to_json() for d in diags],
        }, indent=2))
    else:
        for d in diags:
            print(str(d))
        print(f"wf_check: {g.name}: {len(errors)} error(s), "
              f"{len(diags) - len(errors)} warning(s) "
              f"in {g._preflight_ms} ms")
    if errors or (args.strict and diags):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
