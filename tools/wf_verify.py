#!/usr/bin/env python
"""wf_verify: object-level static verification of an application's kernels.

CLI face of wfverify (``windflow_tpu/analysis/tracecheck.py``), mirroring
``tools/wf_check.py``: point it at the module that builds your PipeGraph
and every *live function object* the runtime will trace or call back —
map/filter/flatmap kernels, reduce combiners, FFAT lift/comb, key
extractors, sink callbacks, the framework's own wf_jit wrapper bodies —
is statically verified for trace-safety (WF80x), recompile hazards
(WF81x), donation safety (WF82x) and, when the graph checkpoints,
replay determinism (WF61x).  Unlike the pure-AST ``tools/wf_lint.py``
this DOES import jax and the application: closures resolve to their
current values, donation is read off the real jit wrappers.

Usage::

    python tools/wf_verify.py APP_MODULE[:ATTR] [MORE...]
    python tools/wf_verify.py ... --json       # machine-readable
    python tools/wf_verify.py ... --strict     # exit 1 on warnings too

Several ``module[:attr]`` targets may be named in one invocation (the CI
stage verifies every bench/chaos entrypoint in one interpreter).  Inline
suppressions (``# wfverify: ok (reason)``) are honored and counted; a
suppression without a reason is rejected and the finding reported.

Exit status: 0 clean, 1 error-severity findings (or any finding under
``--strict``), 2 usage/load failures.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_wf_check():
    spec = importlib.util.spec_from_file_location(
        "wf_check", os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "wf_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("apps", nargs="+",
                    help="APP_MODULE or APP_MODULE:ATTR building the "
                         "PipeGraph (several allowed)")
    ap.add_argument("--json", action="store_true",
                    help="emit per-app reports as one JSON object")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on warnings too")
    args = ap.parse_args(argv)

    load_graph = _load_wf_check().load_graph
    from windflow_tpu.analysis.tracecheck import verify_graph

    out = {}
    total_errors = total_findings = 0
    for app in args.apps:
        g = load_graph(app)
        report = verify_graph(g)
        errors = [d for d in report.diagnostics if d.severity == "error"]
        total_errors += len(errors)
        total_findings += len(report.diagnostics)
        out[app] = {
            "graph": g.name,
            "errors": len(errors),
            "warnings": len(report.diagnostics) - len(errors),
            **report.to_json(),
        }
        if not args.json:
            for d in report.diagnostics:
                print(str(d))
            print(f"wf_verify: {app} ({g.name}): "
                  f"{len(errors)} error(s), "
                  f"{len(report.diagnostics) - len(errors)} warning(s), "
                  f"{len(report.suppressed)} suppressed, "
                  f"{report.checked} callables in {report.check_ms} ms")
    if args.json:
        print(json.dumps(out, indent=2))
    if total_errors or (args.strict and total_findings):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
