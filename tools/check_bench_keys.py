#!/usr/bin/env python
"""Guard the bench e2e decomposition contract (r6 CI check).

The staging-plane work is only provable through two keys in ``bench.py``
output — ``ratio_vs_kernel`` (staged e2e rate over kernel-only rate) and
``staging_share_of_staged_run`` (staged-vs-device-source delta) — and
round-over-round comparisons (BENCH_r05.json baseline: 0.7153 / 0.1964)
silently break if a bench refactor drops either.  This check fails CI
when they disappear.

Usage::

    python tools/check_bench_keys.py             # static: scan bench.py
    python tools/check_bench_keys.py OUT.json    # dynamic: check a bench
                                                 # run's captured output

With a file argument, the last JSON object found in the file (bench.py
prints its result dict as the final stdout line; log lines above it are
skipped) must carry ``e2e.ratio_vs_kernel`` and — unless the
device-source leg errored, which decomposition needs —
``e2e_device_source.decomposition.staging_share_of_staged_run``.
Without arguments, ``bench.py``'s source must still contain the code
paths that emit both keys.

Since the flight-recorder round the bench also publishes a ``latency``
section (``batch_p99_ms`` always; ``e2e_p50_ms``/``e2e_p99_ms`` when the
staged e2e leg ran) recorded into ``bench_history.json`` — the tail
numbers the observability layer steers by (docs/OBSERVABILITY.md).  This
check guards those keys the same way.

Since the static-analysis round the bench also publishes a ``preflight``
section whose ``check_ms`` times ``PipeGraph.check()`` over the
representative e2e pipeline — every ``start()`` now pays that cost, so
it must stay visible in bench_history.json (docs/ANALYSIS.md).  Guarded
here identically.

Since the device-plane round the bench also publishes a ``device``
section from the compile watcher (``compile_ms_total``, ``recompiles``,
``flops_per_batch`` where the backend reports cost analysis —
docs/OBSERVABILITY.md "Device plane").  ``recompiles`` doubles as a
regression tripwire: the bench pipelines pad to fixed capacities, so any
nonzero value is a shape-drift bug.  Guarded here identically.

Since the health round the bench also publishes a ``health`` section
(``stall_events``, ``watchdog_overhead_pct`` — docs/OBSERVABILITY.md
"Health plane") from a watchdog-on pipeline run.  ``stall_events``
doubles as a tripwire: the bench pipeline must run healthy, so any
nonzero value (or a non-OK ``graph_state``) is a watchdog
false-positive or a real runtime regression.  Guarded here identically.

Since the sweep-ledger round the bench also decomposes the roofline:
``roofline.per_hop`` (bytes/tuple + dispatches/batch per operator hop
of the staged e2e pipeline) and ``roofline.attributed_fraction`` (hop
sum over the raw kernel step's measured bytes — docs/OBSERVABILITY.md
"Sweep ledger").  Guarded here identically; their disappearance would
orphan the whole-chain-fusion plan (ROADMAP item 1) of its evidence.

Since the durability round the bench also publishes a ``durability``
section (``checkpoint_ms``, ``restore_ms``, ``checkpoint_bytes``,
``overhead_pct`` of enabling checkpointing vs checkpoint-off on the
representative graph — docs/DURABILITY.md).  ``overhead_pct`` is the
acceptance bound's evidence (<5%); its disappearance would orphan the
whole exactly-once/restore contract of its perf guard.  Guarded here
identically.

Since the shard-plane round the bench also publishes a ``shard``
section (``imbalance_ratio``, ``hot_key_share``,
``ici_bytes_per_tuple`` — docs/OBSERVABILITY.md "Shard plane") from a
seeded Zipf-skew keyby run with the shard ledger on.  The stream is
deterministic, so the skew numbers are regression tripwires (wired
into ``check_bench_regress.py``): a drifting ``imbalance_ratio`` means
the sketch or the placement hash broke, and ``sketch_overhead_pct``
doubles as the <2% budget's evidence.  Guarded here identically.

Since the key-compaction round the bench also publishes a
``compaction`` section (``speedup_vs_sorted``, ``hit_rate``,
``overflow_share``, ``churn_per_sweep`` — docs/PERF.md round 12) from
a seeded Zipf arbitrary-key reduce A/B: the compacted remap path vs
the legacy sorted path on the same batch.  ``hit_rate`` hard-fails
below 0.9 — under that floor the speedup number is measuring the
overflow lane, not the dense fast path — and ``speedup_vs_sorted`` is
tripwired in ``check_bench_regress.py``.  Guarded here identically.

Since the wfverify round the bench also publishes a ``verify`` section
(``findings``, ``check_ms`` — docs/ANALYSIS.md "wfverify") timing the
object-level kernel verifier over the representative pipeline.
``findings`` doubles as a tripwire: the bench kernels ship clean, so
any nonzero unsuppressed count is a verifier false positive or a real
kernel regression — both block.  Guarded here identically.

Since the pallas round the bench also publishes a ``pallas`` section
(``kernels_active``, ``ffat_step_speedup_vs_lax``, ``grouping_speedup``,
``interpret_mode``, ``record_mismatch`` — docs/PERF.md round 14) from a
seeded kernel-vs-lax A/B of the fused FFAT step.  ``record_mismatch``
hard-fails: the kernel-backed step must be bit-identical to the lax
build on the integer-valued seed stream.  ``interpret_mode`` is the
honesty flag — CPU runs emulate the kernels (slower by design), so the
speedup keys are only comparable across runs with the same flag
(``check_bench_regress.py`` gates on it).  Guarded here identically.

Since the megastep round the bench also publishes a ``megastep``
section (``k``, ``e2e_tup_s``, ``speedup_vs_k1``,
``dispatches_per_batch``, ``ratio_vs_kernel`` — docs/PERF.md round 15 /
docs/OBSERVABILITY.md "Megastep in the ledger") from a dispatch-bound
staged-e2e A/B of K folded sweeps vs the K=1 kill switch.  Two hard
gates ride on it: ``e2e_tup_s`` must clear the section's own
``e2e_floor_tup_s`` (CPU: 10x the r14 54.8k per-batch baseline), and
``dispatches_per_batch`` must equal 1/k exactly over the scanned
batches — any excess means the megastep grew extra device dispatches
and the 1-program-per-K-sweeps contract broke.  Guarded here
identically.

Since the latency-plane round the bench also publishes a
``latency_slo`` section (``operating_point``, ``slo_budget_ms``,
``e2e_p99_ms``, ``dominant_op``/``dominant_segment``,
``segment_share`` — docs/OBSERVABILITY.md "Latency plane & SLO") from
a flight-recorder-on pipeline driven at max sustainable throughput:
the ledger-decomposed staged→sunk tail against a declared budget.
Every latency row must carry its ``operating_point`` label — a p99
without the rate it was measured at is not comparable round over
round — and the measured ``e2e_p99_ms`` hard-fails past 2x the
recorded ``slo_budget_ms``: the bench pipelines must run inside their
own declared SLO with margin.  Guarded here identically.

Since the fusion round the bench also publishes a ``fusion`` section
(``fused_chains``, ``dispatches_saved``, ``bytes_saved_per_batch`` —
docs/PERF.md round 10) from the staged e2e run's sweep ledger: the
realized savings of the whole-chain fusion executor
(windflow_tpu/fusion).  Guarded here identically — the section ships
(zeroed) even under the WF_TPU_FUSE=0 kill switch, so its absence is a
bench regression, not a configuration.

Since the calibration round the bench also stamps every result with
``backend``/``device_kind``/``jax_version`` and publishes a
``calibration`` section (the provenance summary: which constants the
modeled numbers were computed from, and whether a calibration store
replaced the defaults — docs/OBSERVABILITY.md "Calibration plane").
Provenance is also a HARD honesty gate here: every provenance tag in
the output must come from the measured/modeled/calibrated(age)/
interpret vocabulary, and a run stamped ``backend == "tpu"`` whose
pallas section still reports ``interpret_mode`` true is lying about
its numbers — the TPU acceptance leg (``tpu_acceptance``: the ROADMAP
item-1 criteria next to their measured values) must never be fed by
the interpreter.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KEYS = ("ratio_vs_kernel", "staging_share_of_staged_run")
LATENCY_KEYS = ("batch_p99_ms", "e2e_p50_ms", "e2e_p99_ms",
                "operating_point")
LATENCY_SLO_KEYS = ("operating_point", "tuples_per_sec", "slo_budget_ms",
                    "e2e_p50_ms", "e2e_p99_ms", "traces_decomposed",
                    "dominant_op", "dominant_segment", "segment_share",
                    "slo_active")
ROOFLINE_KEYS = ("per_hop", "attributed_fraction")
FUSION_KEYS = ("fused_chains", "dispatches_saved", "bytes_saved_per_batch")
DEVICE_KEYS = ("compile_ms_total", "recompiles", "flops_per_batch")
HEALTH_KEYS = ("graph_state", "stall_events", "watchdog_overhead_pct")
DURABILITY_KEYS = ("checkpoint_ms", "restore_ms", "checkpoint_bytes",
                   "overhead_pct")
SHARD_KEYS = ("imbalance_ratio", "hot_key_share", "ici_bytes_per_tuple")
VERIFY_KEYS = ("findings", "check_ms")
IR_AUDIT_KEYS = ("programs_audited", "findings", "check_ms")
WIRE_KEYS = ("wire_bytes_per_tuple", "compression_ratio",
             "staging_share", "decode_dispatch_delta")
COMPACTION_KEYS = ("speedup_vs_sorted", "hit_rate", "overflow_share",
                   "churn_per_sweep")
RESHARD_KEYS = ("plan_apply_ms", "rescale_restore_ms", "keys_moved",
                "post_reshard_imbalance")
PALLAS_KEYS = ("kernels_active", "ffat_step_speedup_vs_lax",
               "grouping_speedup", "interpret_mode", "record_mismatch",
               "provenance")
MEGASTEP_KEYS = ("k", "e2e_tup_s", "e2e_floor_tup_s", "speedup_vs_k1",
                 "dispatches_per_batch", "ratio_vs_kernel")
TENANT_KEYS = ("tenants", "hbm_attributed_fraction", "budget_pressure",
               "ledger_overhead_pct")
CALIBRATION_KEYS = ("schema", "enabled", "constants")
STAMP_KEYS = ("backend", "device_kind", "jax_version")
TPU_ACCEPTANCE_KEYS = ("grouping_speedup", "grouping_speedup_target",
                       "grouping_speedup_met", "e2e_wire_bytes_per_tuple",
                       "ici_bytes_per_tuple", "megastep_ratio_vs_kernel",
                       "interpret_mode")
# the full provenance vocabulary (docs/OBSERVABILITY.md "Calibration
# plane"): three fixed tags plus the age-stamped calibrated(...) form
PROVENANCE_FIXED = ("measured", "modeled", "interpret")


def legal_provenance(tag) -> bool:
    return tag in PROVENANCE_FIXED or (
        isinstance(tag, str) and tag.startswith("calibrated("))


def fail(msg: str) -> None:
    print(f"check_bench_keys: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def check_source() -> None:
    with open(os.path.join(REPO, "bench.py")) as f:
        src = f.read()
    missing = [k for k in KEYS if f'"{k}"' not in src]
    if missing:
        fail(f"bench.py no longer emits {missing} — the e2e "
             "decomposition contract (docs/PERF.md) is broken")
    for section, keys, contract in (
            ("latency", LATENCY_KEYS, "docs/OBSERVABILITY.md"),
            ("latency_slo", LATENCY_SLO_KEYS,
             "latency ledger — docs/OBSERVABILITY.md latency plane "
             "& SLO"),
            ("roofline", ROOFLINE_KEYS,
             "sweep ledger — docs/OBSERVABILITY.md sweep-ledger"),
            ("fusion", FUSION_KEYS,
             "whole-chain fusion — docs/PERF.md round 10"),
            ("preflight", ("check_ms",), "docs/ANALYSIS.md"),
            ("verify", VERIFY_KEYS,
             "wfverify — docs/ANALYSIS.md wfverify section"),
            ("ir_audit", IR_AUDIT_KEYS,
             "wfir — docs/ANALYSIS.md wfir section"),
            ("device", DEVICE_KEYS,
             "compile watcher — docs/OBSERVABILITY.md device-plane"),
            ("health", HEALTH_KEYS,
             "watchdog — docs/OBSERVABILITY.md health-plane"),
            ("shard", SHARD_KEYS,
             "shard plane — docs/OBSERVABILITY.md shard-plane"),
            ("compaction", COMPACTION_KEYS,
             "key compaction — docs/PERF.md round 12"),
            ("wire", WIRE_KEYS,
             "wire compression — docs/PERF.md round 13 / "
             "docs/OBSERVABILITY.md wire plane"),
            ("durability", DURABILITY_KEYS,
             "checkpoint/restore — docs/DURABILITY.md"),
            ("reshard", RESHARD_KEYS,
             "reshard executor + rescale restore — "
             "docs/OBSERVABILITY.md reshard-executor / "
             "docs/DURABILITY.md rescale-on-restore"),
            ("pallas", PALLAS_KEYS,
             "Pallas kernels — docs/PERF.md round 14"),
            ("megastep", MEGASTEP_KEYS,
             "megastep executor — docs/PERF.md round 15 / "
             "docs/OBSERVABILITY.md megastep-in-the-ledger"),
            ("tenant", TENANT_KEYS,
             "tenant plane — docs/OBSERVABILITY.md tenant-plane"),
            # the calibration section's inner keys come from
            # provenance_summary() (not bench.py literals) — the static
            # pass guards the section name + the hardware stamp;
            # check_output validates the summary's shape dynamically
            ("calibration", STAMP_KEYS,
             "calibration plane — docs/OBSERVABILITY.md "
             "calibration-plane"),
            ("tpu_acceptance", TPU_ACCEPTANCE_KEYS,
             "TPU acceptance leg — ROADMAP item 1 / "
             "docs/OBSERVABILITY.md calibration-plane")):
        missing = [k for k in keys if f'"{k}"' not in src] \
            + ([] if f'"{section}"' in src else [section])
        if missing:
            fail(f"bench.py no longer emits the {section} section keys "
                 f"{missing} ({contract} contract)")
    print("check_bench_keys: OK (bench.py source emits "
          + ", ".join(KEYS + ("latency", "latency_slo", "preflight",
                              "verify", "device", "health", "shard",
                              "compaction", "fusion", "durability",
                              "reshard", "pallas")) + ")")


def last_json_object(path: str):
    """The bench result dict: last line of the file that parses as a JSON
    object (bench.py prints it as its final stdout line)."""
    obj = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line.startswith("{"):
                try:
                    cand = json.loads(line)
                except ValueError:
                    continue
                if isinstance(cand, dict):
                    obj = cand
    return obj


def check_output(path: str) -> None:
    result = last_json_object(path)
    if result is None:
        fail(f"no JSON result object found in {path}")
    e2e = result.get("e2e")
    if not isinstance(e2e, dict):
        fail(f"bench result has no 'e2e' section "
             f"(e2e_error={result.get('e2e_error')!r})")
    if "ratio_vs_kernel" not in e2e:
        fail("'e2e.ratio_vs_kernel' missing from bench output")
    dev = result.get("e2e_device_source")
    if isinstance(dev, dict):
        decomp = dev.get("decomposition", {})
        if "staging_share_of_staged_run" not in decomp:
            fail("'e2e_device_source.decomposition."
                 "staging_share_of_staged_run' missing from bench output")
        share = decomp["staging_share_of_staged_run"]
    elif "e2e_device_source_error" in result:
        # the device-source leg can fail for environment reasons (e.g. a
        # flaky TPU tunnel); the decomposition needs both legs, so only
        # report — the ratio key above is still enforced
        print("check_bench_keys: note: device-source leg errored "
              f"({result['e2e_device_source_error']!r}); decomposition "
              "absent for this run")
        share = None
    else:
        fail("bench output has neither 'e2e_device_source' nor "
             "'e2e_device_source_error'")
    lat = result.get("latency")
    if not isinstance(lat, dict):
        fail("'latency' section missing from bench output")
    if "batch_p99_ms" not in lat:
        fail("'latency.batch_p99_ms' missing from bench output")
    if not lat.get("operating_point"):
        # unlabeled latency rows are not comparable round over round:
        # a p99 means nothing without the rate it was measured at
        fail("'latency.operating_point' missing — latency rows must "
             "name their operating point")
    lslo = result.get("latency_slo")
    if isinstance(lslo, dict):
        missing = [k for k in LATENCY_SLO_KEYS if k not in lslo]
        if missing:
            fail(f"'latency_slo' section missing {missing} from bench "
                 "output")
        if not lslo.get("operating_point"):
            fail("'latency_slo.operating_point' empty — latency rows "
                 "must name their operating point")
        budget = lslo.get("slo_budget_ms")
        p99 = lslo.get("e2e_p99_ms")
        if isinstance(budget, (int, float)) and budget > 0 \
                and isinstance(p99, (int, float)) and p99 > 2 * budget:
            # the shipped bench pipeline must run inside its own
            # declared SLO with margin: a p99 past 2x the budget is a
            # latency regression on the representative shape, not noise
            fail(f"latency_slo e2e_p99_ms={p99} exceeds 2x the recorded "
                 f"SLO budget ({budget} ms) on the shipped bench shape")
        if not lslo.get("traces_decomposed"):
            fail("latency_slo leg decomposed no traces — the ledger's "
                 "harvest or the recorder's sampling broke")
    else:
        # the latency-SLO leg is an in-process flight-recorder run with
        # no environmental failure mode — its absence IS the regression
        fail("bench latency_slo section absent or errored "
             f"(latency_slo_error={result.get('latency_slo_error')!r})")
    dev_sec = result.get("device")
    if isinstance(dev_sec, dict):
        missing = [k for k in DEVICE_KEYS if k not in dev_sec]
        if missing:
            fail(f"'device' section missing {missing} from bench output")
        if dev_sec.get("recompiles"):
            # fixed-capacity pipelines must never re-trace: a nonzero
            # recompile count is the shape-drift regression the compile
            # watcher exists to catch
            fail(f"bench run recompiled {dev_sec['recompiles']} time(s) — "
                 "recompilation storm in a fixed-capacity pipeline")
    else:
        # like preflight, the watcher is environment-independent: its
        # absence IS the observability regression this guard catches
        fail("bench device section absent or errored "
             f"(device_error={result.get('device_error')!r})")
    health = result.get("health")
    if isinstance(health, dict):
        missing = [k for k in HEALTH_KEYS if k not in health]
        if missing:
            fail(f"'health' section missing {missing} from bench output")
        if health.get("stall_events") or health.get("graph_state") != "OK":
            # the bench pipeline must run healthy: a stall event or a
            # degraded graph verdict here is either a watchdog
            # false-positive or a real runtime regression — both block
            fail(f"bench health run degraded: {health}")
    else:
        # like preflight, the watchdog leg is device-free — its absence
        # IS the observability regression this guard catches
        fail("bench health section absent or errored "
             f"(health_error={result.get('health_error')!r})")
    roof = result.get("roofline")
    if not isinstance(roof, dict):
        fail("'roofline' section missing from bench output")
    if isinstance(result.get("e2e"), dict):
        # the staged e2e leg ran: the sweep ledger must have attributed
        # its hops (docs/OBSERVABILITY.md "Sweep ledger")
        if not isinstance(roof.get("per_hop"), dict) \
                or not roof["per_hop"]:
            fail("'roofline.per_hop' missing or empty — the sweep "
                 "ledger's per-hop attribution is broken")
        if roof.get("measured_bytes_per_tuple") \
                and not isinstance(roof.get("attributed_fraction"),
                                   (int, float)):
            fail("'roofline.attributed_fraction' missing although the "
                 "kernel step's bytes were measured — per-hop bytes "
                 "did not attribute")
    fus = result.get("fusion")
    if isinstance(fus, dict):
        missing = [k for k in FUSION_KEYS if k not in fus]
        if missing:
            fail(f"'fusion' section missing {missing} from bench output")
    else:
        # the fusion section derives from the e2e sweep ledger with no
        # environmental failure mode (it ships zeroed under the
        # WF_TPU_FUSE kill switch) — its absence IS the regression
        fail("bench fusion section absent from bench output")
    shard = result.get("shard")
    if isinstance(shard, dict):
        missing = [k for k in SHARD_KEYS if k not in shard]
        if missing:
            fail(f"'shard' section missing {missing} from bench output")
        hot = shard.get("hot_key")
        if hot is not None and hot != 7:
            # the shard leg injects key 7 as 40% of the stream — the
            # ledger failing to name it means the sketch broke
            fail(f"shard leg misattributed the seeded hot key: got "
                 f"{hot!r}, injected 7")
        ovh = shard.get("sketch_overhead_pct")
        if isinstance(ovh, (int, float)) and ovh > 2.0:
            fail(f"shard sketch overhead {ovh}% exceeds the 2% budget "
                 "(docs/OBSERVABILITY.md shard plane)")
    else:
        # the shard leg runs on any backend with no environmental
        # failure mode — its absence IS the regression
        fail("bench shard section absent or errored "
             f"(shard_error={result.get('shard_error')!r})")
    compc = result.get("compaction")
    if isinstance(compc, dict):
        missing = [k for k in COMPACTION_KEYS if k not in compc]
        if missing:
            fail(f"'compaction' section missing {missing} from bench "
                 "output")
        hr = compc.get("hit_rate")
        if not isinstance(hr, (int, float)) or hr < 0.9:
            # the leg seeds the remap with the Zipf stream's hot set
            # before measuring: a hit rate under 0.9 means admission,
            # the lookup, or the seeding walk broke — the speedup
            # number above it would be measuring the overflow lane
            fail(f"compaction hit_rate={hr!r} below the 0.9 floor on "
                 "the seeded Zipf leg (docs/PERF.md round 12)")
        if compc.get("big_fallbacks"):
            # ~2% of lanes miss per batch — nowhere near the
            # capacity//32 overflow budget, so any full-width fallback
            # here means the miss accounting broke
            fail(f"compaction leg took {compc['big_fallbacks']} "
                 "full-width sorted fallbacks on a 2%-miss stream")
    else:
        # the compaction leg is an in-process kernel A/B with no
        # environmental failure mode — its absence IS the regression
        fail("bench compaction section absent or errored "
             f"(compaction_error={result.get('compaction_error')!r})")
    wr = result.get("wire")
    if isinstance(wr, dict):
        missing = [k for k in WIRE_KEYS if k not in wr]
        if missing:
            fail(f"'wire' section missing {missing} from bench output")
        cr = wr.get("compression_ratio")
        if not isinstance(cr, (int, float)) or cr < 1.5:
            # the seeded leg runs the e2e record spec (dict key lane,
            # raw f32 value, cadence ts): under 1.5x means a codec,
            # the selection, or the encoder broke — the wire round's
            # whole claim (docs/PERF.md round 13)
            fail(f"wire compression_ratio={cr!r} below the 1.5x floor "
                 "on the e2e record spec")
        dd = wr.get("decode_dispatch_delta")
        if dd:
            # the decode is traced INTO the existing unpack program;
            # ANY nonzero per-batch dispatch delta means it grew its
            # own dispatch — the zero-extra-dispatch contract broke
            fail(f"wire decode_dispatch_delta={dd} — decompression "
                 "added device dispatches (must ride staging.unpack)")
    else:
        # the wire leg is an in-process seeded A/B with no
        # environmental failure mode — its absence IS the regression
        fail("bench wire section absent or errored "
             f"(wire_error={result.get('wire_error')!r})")
    dura = result.get("durability")
    if isinstance(dura, dict):
        missing = [k for k in DURABILITY_KEYS if k not in dura]
        if missing:
            fail(f"'durability' section missing {missing} from bench "
                 "output")
        ov = dura.get("overhead_pct")
        if isinstance(ov, (int, float)) and ov > 15.0:
            # the budget is 5% (docs/DURABILITY.md), but overhead_pct is
            # the ratio of two short single-shot timed runs whose own
            # noise is ~±13% on this infra (check_bench_regress excludes
            # it for the same reason) — hard-fail only past a
            # noise-padded bound a real hot-path regression clears
            fail(f"durability overhead_pct={ov} is far past the 5% "
                 "budget — checkpointing has become a hot-path cost")
        elif isinstance(ov, (int, float)) and ov > 5.0:
            print(f"check_bench_keys: note: durability overhead_pct={ov} "
                  "above the 5% budget — single-sample ratio, rerun to "
                  "separate regression from timing noise")
    else:
        # the durability leg runs against the in-memory broker with no
        # environmental failure mode — its absence IS the regression
        fail("bench durability section absent or errored "
             f"(durability_error={result.get('durability_error')!r})")
    rsh = result.get("reshard")
    if isinstance(rsh, dict):
        missing = [k for k in RESHARD_KEYS if k not in rsh]
        if missing:
            fail(f"'reshard' section missing {missing} from bench "
                 "output")
        if not rsh.get("keys_moved"):
            # the seeded colocated-warm-pair stream is deterministic:
            # a leg that moved no keys means the trigger, the plan, or
            # the apply path broke
            fail("reshard leg moved no keys on the seeded "
                 "colocated-warm-pair stream — the executor's "
                 "trigger→plan→apply path broke")
        pri = rsh.get("post_reshard_imbalance")
        if isinstance(pri, (int, float)) and pri > 2.5:
            fail(f"post_reshard_imbalance={pri} — the applied move did "
                 "not repair the window imbalance on the seeded stream")
    else:
        # the reshard leg runs in-process on a seeded stream with no
        # environmental failure mode — its absence IS the regression
        fail("bench reshard section absent or errored "
             f"(reshard_error={result.get('reshard_error')!r})")
    pal = result.get("pallas")
    if isinstance(pal, dict):
        missing = [k for k in PALLAS_KEYS if k not in pal]
        if missing:
            fail(f"'pallas' section missing {missing} from bench output")
        if pal.get("record_mismatch"):
            # the canary: the kernel-backed step's first batch must be
            # BIT-IDENTICAL to the lax build's on the integer-valued
            # seed stream — any mismatch is a kernel correctness
            # regression, not a perf question (docs/PERF.md round 14)
            fail("pallas record-mismatch canary tripped: the "
                 "kernel-backed FFAT step diverged from the lax path")
        if pal.get("kernels_active") and pal.get("interpret_mode") is None:
            fail("pallas section reports active kernels without an "
                 "interpret_mode flag — the speedup numbers are "
                 "uninterpretable without it")
    else:
        # the pallas leg is an in-process kernel A/B with no
        # environmental failure mode — its absence IS the regression
        fail("bench pallas section absent or errored "
             f"(pallas_error={result.get('pallas_error')!r})")
    msec = result.get("megastep")
    if isinstance(msec, dict):
        missing = [k for k in MEGASTEP_KEYS if k not in msec]
        if missing:
            fail(f"'megastep' section missing {missing} from bench "
                 "output")
        floor = msec.get("e2e_floor_tup_s") or 0
        tps = msec.get("e2e_tup_s")
        if isinstance(tps, (int, float)) and floor and tps < floor:
            # the r15 acceptance floor: the K-folded staged e2e must
            # hold 10x the r14 per-batch CPU baseline — falling under
            # it means the megastep stopped scanning (check the
            # fallback_batches count) or the driver loop regressed
            fail(f"megastep e2e_tup_s={tps} under the "
                 f"{floor} floor (docs/PERF.md round 15)")
        kk, dpb = msec.get("k"), msec.get("dispatches_per_batch")
        if isinstance(kk, int) and kk > 1:
            if not isinstance(dpb, (int, float)):
                fail("megastep ran with K>1 but dispatches_per_batch "
                     "is absent — no batch was ever scanned (the "
                     "plane downgraded or the warm check never passed)")
            if abs(dpb * kk - 1.0) > 1e-6:
                # the 1-program-per-K-sweeps contract, pinned by the
                # jit registry's megastep.* dispatch count: over the
                # scanned batches the ratio is 1/K EXACTLY — warmup
                # and EOS-remainder batches are reported separately
                fail(f"megastep dispatches_per_batch={dpb} != 1/{kk} — "
                     "the folded program grew extra device dispatches")
    else:
        # the megastep leg is an in-process staged-e2e A/B with no
        # environmental failure mode — its absence IS the regression
        fail("bench megastep section absent or errored "
             f"(megastep_error={result.get('megastep_error')!r})")
    tenant = result.get("tenant")
    if isinstance(tenant, dict):
        missing = [k for k in TENANT_KEYS if k not in tenant]
        if missing:
            fail(f"'tenant' section missing {missing} from bench "
                 "output")
        frac = tenant.get("hbm_attributed_fraction")
        if not isinstance(frac, (int, float)) or frac < 0.9:
            # the reconciliation floor (docs/OBSERVABILITY.md tenant
            # plane): the ledger must attribute at least 90% of the
            # process's staged device bytes to tenants — under it the
            # per-tenant numbers are not trustworthy enough to schedule
            # against
            fail(f"tenant hbm_attributed_fraction={frac!r} below the "
                 "0.9 reconciliation floor on the seeded two-tenant "
                 "leg")
        ovh = tenant.get("ledger_overhead_pct")
        if isinstance(ovh, (int, float)) and ovh > 2.0:
            fail(f"tenant ledger overhead {ovh}% exceeds the 2% budget "
                 "(docs/OBSERVABILITY.md tenant plane)")
    else:
        # the tenant leg is an in-process seeded two-graph run with no
        # environmental failure mode — its absence IS the regression
        fail("bench tenant section absent or errored "
             f"(tenant_error={result.get('tenant_error')!r})")
    ver = result.get("verify")
    if isinstance(ver, dict):
        missing = [k for k in VERIFY_KEYS if k not in ver]
        if missing:
            fail(f"'verify' section missing {missing} from bench output")
        if ver.get("findings"):
            # the bench pipeline's kernels ship clean: a nonzero
            # unsuppressed finding count is either a wfverify false
            # positive or a real kernel regression — both block
            fail(f"bench verify run reported {ver['findings']} "
                 "unsuppressed wfverify finding(s) on the shipped "
                 "bench kernels")
    else:
        # wfverify is device-free (static analysis of live callables) —
        # its absence IS the analysis regression this guard catches
        fail("bench verify section absent or errored "
             f"(preflight_error={result.get('preflight_error')!r})")
    ira = result.get("ir_audit")
    if isinstance(ira, dict):
        missing = [k for k in IR_AUDIT_KEYS if k not in ira]
        if missing:
            fail(f"'ir_audit' section missing {missing} from bench "
                 "output")
        if not ira.get("programs_audited"):
            # the bench legs above compiled dozens of wf_jit programs
            # through the compile watcher: zero captured lowerings means
            # the registry hook or the capture path broke
            fail("bench ir_audit audited zero programs — the compile "
                 "watcher's lowering capture (analysis/ir_audit.py) "
                 "stopped recording")
        if ira.get("findings"):
            # shipped bench programs audit clean on the IR: a nonzero
            # WF9xx count is a lowering regression (a host callback, a
            # 64-bit survivor, a donation miss in a compiled program)
            # or an auditor false positive — both block
            fail(f"bench ir_audit reported {ira['findings']} WF9xx "
                 "finding(s) on the shipped bench programs")
    else:
        # the IR audit parses lowerings already captured in-process —
        # device-free, no environmental failure mode: its absence IS
        # the analysis regression this guard catches
        fail("bench ir_audit section absent or errored "
             f"(ir_audit_error={result.get('ir_audit_error')!r})")
    pf = result.get("preflight")
    if isinstance(pf, dict):
        if "check_ms" not in pf:
            fail("'preflight.check_ms' missing from bench output")
    else:
        # unlike the device-source leg, preflight is device-free — it has
        # no legitimate environmental failure mode, so an error IS the
        # analysis regression this guard exists to catch
        fail("bench preflight timing absent or errored "
             f"(preflight_error={result.get('preflight_error')!r})")
    for k in STAMP_KEYS:
        if not result.get(k):
            # an unstamped result can be diffed against any hardware's
            # history — check_bench_regress's comparability gate needs
            # the stamp to refuse cross-hardware comparisons
            fail(f"bench result missing the {k!r} hardware stamp "
                 "(docs/OBSERVABILITY.md calibration plane)")
    calib = result.get("calibration")
    if isinstance(calib, dict):
        missing = [k for k in CALIBRATION_KEYS if k not in calib]
        if missing:
            fail(f"'calibration' section missing {missing} from bench "
                 "output")
        for key, slot in (calib.get("constants") or {}).items():
            tag = (slot or {}).get("provenance") \
                if isinstance(slot, dict) else None
            if not legal_provenance(tag):
                fail(f"calibration constant {key!r} carries illegal "
                     f"provenance {tag!r} — the vocabulary is "
                     "measured/modeled/calibrated(age)/interpret")
    else:
        # the provenance summary is pure-host bookkeeping with no
        # environmental failure mode — its absence IS the regression
        fail("bench calibration section absent or errored "
             f"(calibration_error={result.get('calibration_error')!r})")
    if pal.get("provenance") is not None \
            and not legal_provenance(pal["provenance"]):
        fail(f"pallas provenance {pal['provenance']!r} is not in the "
             "measured/modeled/calibrated(age)/interpret vocabulary")
    if result.get("backend") == "tpu":
        # the honesty gate: a TPU-stamped row whose kernel timings came
        # from the Pallas interpreter is not a TPU measurement — the
        # fallback must never masquerade as acceptance evidence
        if pal.get("interpret_mode"):
            fail("result stamped backend=tpu but the pallas section "
                 "ran under the interpreter (interpret_mode=true) — "
                 "interpreter timings must never be recorded as TPU "
                 "measurements")
        acc = result.get("tpu_acceptance")
        if not isinstance(acc, dict):
            fail("backend=tpu result has no 'tpu_acceptance' section "
                 "(ROADMAP item 1 acceptance numbers)")
        missing = [k for k in TPU_ACCEPTANCE_KEYS if k not in acc]
        if missing:
            fail(f"'tpu_acceptance' section missing {missing} from "
                 "bench output")
        if acc.get("interpret_mode"):
            fail("tpu_acceptance leg claims interpret-mode numbers — "
                 "acceptance evidence must be compiled-chip measurements")
        for k in ("grouping_provenance", "wire_provenance",
                  "ici_provenance", "megastep_provenance"):
            if k in acc and not legal_provenance(acc[k]):
                fail(f"tpu_acceptance {k}={acc[k]!r} is not a legal "
                     "provenance tag")
    if isinstance(result.get("e2e"), dict):
        missing = [k for k in ("e2e_p50_ms", "e2e_p99_ms") if k not in lat]
        if missing:
            fail(f"latency section missing {missing} although the staged "
                 "e2e leg ran")
    print("check_bench_keys: OK (ratio_vs_kernel="
          f"{e2e['ratio_vs_kernel']}, staging_share_of_staged_run="
          f"{share}, latency={lat})")


if __name__ == "__main__":
    if len(sys.argv) > 1:
        check_output(sys.argv[1])
    else:
        check_source()
